#!/usr/bin/env python
"""Parity harness: the five BASELINE eval configs, end-to-end.

Runs every driver surface (``SparkModel``, ``ElephasEstimator``,
``HyperParamModel``) on the BASELINE.json workloads and emits one JSON
line per config::

    {"config": ..., "mode": ..., "samples_per_sec": ..., "final_val_acc": ...,
     "real_data": ..., "epochs": ..., "train_rows": ...}

Data resolution: real datasets when present under ``$ELEPHAS_DATA_DIR``
(see ``elephas_tpu/data/datasets.py`` for drop-in file formats), else
deterministic synthetic stand-ins — ``real_data`` records which was used;
only real-data rows are comparable to published MNIST/CIFAR/IMDB numbers.

Usage::

    python parity.py                 # all five configs
    python parity.py --quick        # small slices (CI smoke)
    python parity.py --configs mnist_mlp_sync,cifar10_resnet18_hogwild
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


class EpochTimer:
    """Fit callback recording a wall-clock timestamp at every epoch end.

    Steady-state throughput comes from epochs 2..N (the span between the
    first and last timestamp): epoch 1 pays jit compilation, so dividing
    total rows by total wall time understates the framework's real rate
    by orders of magnitude on short runs (VERDICT r2 weak #1).
    """

    def __init__(self):
        self.times = []

    def __call__(self, epoch, state, metrics):
        self.times.append(time.perf_counter())


def micro_control() -> float:
    """Pinned micro-workload measuring THIS session's effective machine
    speed, run once at harness start: single device, synthetic
    fixed-seed data, fixed shapes — nothing a code change under test
    touches. Its steady samples/sec lands in every emitted row as
    ``control_samples_per_sec``, so rows from different sessions compare
    via ``ratio_to_control`` instead of raw rates (PARITY.md round 5:
    same-code throughput moved 10–45% day-to-day with the dev tunnel,
    which silently eats cross-session comparisons).
    """
    import jax
    from elephas_tpu import compile_model
    from elephas_tpu.data.rdd import ShardedDataset
    from elephas_tpu.engine.sync import SyncTrainer
    from elephas_tpu.models import get_model
    from elephas_tpu.parallel.mesh import build_mesh

    rng = np.random.default_rng(0)  # pinned: identical tensors every run
    n, dim = 4096, 784
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
    net = compile_model(
        get_model("mlp", features=(128, 128), num_classes=10),
        optimizer={"name": "adam", "learning_rate": 1e-3},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=(dim,),
    )
    mesh = build_mesh(num_data=1, devices=[jax.devices()[0]])
    trainer = SyncTrainer(net, mesh, frequency="epoch")
    data = ShardedDataset(x, y, 1)
    trainer.fit(data, epochs=1, batch_size=64)  # compile + warm-up
    epochs = 3
    t0 = time.perf_counter()
    trainer.fit(data, epochs=epochs, batch_size=64)
    return n * epochs / (time.perf_counter() - t0)


def _record(name, mode, history, n_rows, epochs, secs, real, timer=None, extra=None):
    val_keys = [k for k in history if k.startswith("val_") and "acc" in k]
    acc_keys = [k for k in history if "acc" in k and not k.startswith("val_")]
    if timer is not None and len(timer.times) >= 2:
        span = timer.times[-1] - timer.times[0]
        rate = n_rows * (len(timer.times) - 1) / span
        timing = "steady_state"  # excludes epoch 1 (compile + warmup)
    else:
        rate = n_rows * epochs / secs
        timing = "total_incl_compile"
    rec = {
        "config": name,
        "mode": mode,
        "samples_per_sec": round(rate, 2),
        "timing": timing,
        "total_secs": round(secs, 2),
        "final_val_acc": round(float(history[val_keys[0]][-1]), 4) if val_keys else None,
        "final_train_acc": round(float(history[acc_keys[0]][-1]), 4) if acc_keys else None,
        "real_data": real,
        "epochs": epochs,
        "train_rows": n_rows,
    }
    if extra:
        rec.update(extra)
    return rec


# ----------------------------------------------------------------- configs


def mnist_mlp_sync(quick: bool):
    """BASELINE config 1: MNIST MLP, synchronous, 4 partitions."""
    from elephas_tpu import SparkModel, compile_model, to_simple_rdd
    from elephas_tpu.data.datasets import load_mnist, one_hot
    from elephas_tpu.models import get_model

    (xtr, ytr), (xte, yte), real = load_mnist()
    if quick:
        xtr, ytr = xtr[:2048], ytr[:2048]
        xte, yte = xte[:512], yte[:512]
    x = xtr.astype(np.float32) / 255.0
    y = one_hot(ytr, 10)
    xv = xte.astype(np.float32) / 255.0
    yv = one_hot(yte, 10)
    net = compile_model(
        get_model("mlp", features=(128, 128), num_classes=10, dropout_rate=0.1),
        optimizer={"name": "adam", "learning_rate": 1e-3},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=x.shape[1:],
    )
    epochs = 2 if quick else 5
    model = SparkModel(net, mode="synchronous", frequency="epoch", num_workers=4)
    timer = EpochTimer()
    t0 = time.perf_counter()
    history = model.fit(
        to_simple_rdd(None, x, y, 4), epochs=epochs, batch_size=32,
        validation_data=(xv, yv), callbacks=[timer],
    )
    secs = time.perf_counter() - t0
    return _record("mnist_mlp_sync", "synchronous", history, len(x), epochs, secs,
                   real, timer)


def mnist_cnn_async(quick: bool):
    """BASELINE config 2: MNIST CNN, asynchronous PS."""
    from elephas_tpu import SparkModel, compile_model, to_simple_rdd
    from elephas_tpu.data.datasets import load_mnist, one_hot
    from elephas_tpu.models import get_model

    (xtr, ytr), (xte, yte), real = load_mnist()
    if quick:
        xtr, ytr = xtr[:2048], ytr[:2048]
        xte, yte = xte[:512], yte[:512]
    x = (xtr.astype(np.float32) / 255.0)[..., None]  # NHWC
    y = one_hot(ytr, 10)
    xv = (xte.astype(np.float32) / 255.0)[..., None]
    yv = one_hot(yte, 10)
    net = compile_model(
        get_model("cnn", channels=(32, 64), dense_width=128, num_classes=10),
        optimizer={"name": "adam", "learning_rate": 1e-3},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=x.shape[1:],
    )
    epochs = 2 if quick else 3
    import jax

    n_workers = len(jax.devices())
    model = SparkModel(net, mode="asynchronous", frequency="epoch", num_workers=n_workers)
    timer = EpochTimer()
    t0 = time.perf_counter()
    history = model.fit(
        to_simple_rdd(None, x, y, n_workers), epochs=epochs, batch_size=64,
        validation_data=(xv, yv), callbacks=[timer],
    )
    secs = time.perf_counter() - t0
    if getattr(model, "last_epoch_end_times", None):
        timer.times = model.last_epoch_end_times  # true worker cadence
    return _record("mnist_cnn_async", "asynchronous", history, len(x), epochs, secs,
                   real, timer)


def imdb_lstm_estimator(quick: bool):
    """BASELINE config 3: IMDB LSTM through the ML-pipeline estimator."""
    from elephas_tpu.data.datasets import load_imdb
    from elephas_tpu.data.dataframe import to_data_frame
    from elephas_tpu.ml.ml_model import ElephasEstimator

    maxlen = 120 if quick else 200
    (xtr, ytr), (xte, yte), real = load_imdb(num_words=20000, maxlen=maxlen)
    if quick:
        xtr, ytr = xtr[:2048], ytr[:2048]
        xte, yte = xte[:512], yte[:512]
    df = to_data_frame(None, xtr.astype(np.float32), ytr.astype(np.float32))
    epochs = 2 if quick else 3
    import jax

    n_workers = len(jax.devices())
    timer = EpochTimer()
    est = ElephasEstimator(
        callbacks=[timer],
        keras_model_config={
            "name": "lstm",
            "kwargs": {
                "vocab_size": 20000, "embed_dim": 64, "hidden_dim": 64,
                "num_classes": 2,
            },
            "input_shape": [maxlen],
            "input_dtype": "int32",
        },
        optimizer_config={"name": "adam", "learning_rate": 1e-3},
        loss="sparse_categorical_crossentropy",
        metrics=["acc"],
        mode="synchronous",
        frequency="epoch",
        epochs=epochs,
        batch_size=32,
        num_workers=n_workers,
        categorical=False,
        nb_classes=2,
    )
    t0 = time.perf_counter()
    transformer = est.fit(df)
    secs = time.perf_counter() - t0
    out = transformer.transform(
        to_data_frame(None, xte.astype(np.float32), yte.astype(np.float32))
    )
    preds = np.asarray(out["prediction"])
    val_acc = float((preds.argmax(-1) == yte).mean())
    history = {"val_acc": [val_acc]}
    return _record(
        "imdb_lstm_estimator", "estimator", history, len(xtr), epochs, secs, real,
        timer,
    )


def cifar10_resnet18_hogwild(quick: bool):
    """BASELINE config 4 (the flagship): CIFAR-10 ResNet-18, hogwild."""
    from elephas_tpu import SparkModel, compile_model, to_simple_rdd
    from elephas_tpu.data.datasets import load_cifar10, one_hot
    from elephas_tpu.models import get_model

    (xtr, ytr), (xte, yte), real = load_cifar10()
    if quick:
        xtr, ytr = xtr[:2048], ytr[:2048]
        xte, yte = xte[:512], yte[:512]
    mean = np.array([0.4914, 0.4822, 0.4465], np.float32) * 255.0
    std = np.array([0.247, 0.243, 0.261], np.float32) * 255.0
    x = (xtr.astype(np.float32) - mean) / std
    y = one_hot(ytr, 10)
    xv = (xte.astype(np.float32) - mean) / std
    yv = one_hot(yte, 10)
    import jax

    # bf16 compute/norm-output on TPU (the framework's native config —
    # PROFILE.md §1; f32 stats per flax semantics), f32 on CPU CI.
    dtype = "bfloat16" if jax.default_backend() == "tpu" else "float32"
    net = compile_model(
        get_model("resnet18", num_classes=10, width=16 if quick else 64,
                  dtype=dtype),
        optimizer={"name": "momentum", "learning_rate": 0.05},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=x.shape[1:],
    )
    epochs = 2 if quick else 4
    n_workers = len(jax.devices())
    # Per-workload compile autotune (VERDICT r4 #5): the flagship fit
    # picks its own compile options from a 2-batch A/B; the choice is
    # recorded in the emitted row (``compile_autotune``).
    model = SparkModel(net, mode="hogwild", frequency="epoch",
                       num_workers=n_workers, autotune=True)
    timer = EpochTimer()
    t0 = time.perf_counter()
    history = model.fit(
        to_simple_rdd(None, x, y, n_workers), epochs=epochs, batch_size=512,
        validation_data=(xv, yv), callbacks=[timer],
    )
    secs = time.perf_counter() - t0
    if getattr(model, "last_epoch_end_times", None):
        timer.times = model.last_epoch_end_times  # true worker cadence
    return _record(
        "cifar10_resnet18_hogwild", "hogwild", history, len(x), epochs, secs, real,
        timer,
        extra={"compile_autotune": history.get("compile_autotune")},
    )


def hyperparam_search(quick: bool):
    """BASELINE config 5: distributed random search (hyperas analogue)."""
    from elephas_tpu import compile_model
    from elephas_tpu.data.datasets import load_mnist, one_hot
    from elephas_tpu.engine.sync import SyncTrainer
    from elephas_tpu.hyperparam import HyperParamModel, current_trial_device, hp
    from elephas_tpu.models import get_model
    from elephas_tpu.data.rdd import ShardedDataset
    from elephas_tpu.parallel.mesh import build_mesh

    (xtr, ytr), (xte, yte), real = load_mnist()
    n = 2048 if quick else 4096
    x = xtr[:n].astype(np.float32) / 255.0
    y = one_hot(ytr[:n], 10)
    xv = xte[:1024].astype(np.float32) / 255.0
    yv = one_hot(yte[:1024], 10)

    from elephas_tpu.hyperparam import width_bucket

    # Executable sharing (VERDICT r4 #6): widths are PADDED to bucket
    # shapes with the true width masked (models.mlp.MaskedMLP) and the
    # lr rides opt_state ("injected"), so the whole search compiles
    # len(BUCKETS) executables instead of one per fresh (width) —
    # ~12s per fresh shape on this chip (r4 parity_results.jsonl).
    BUCKETS = (128, 256)

    def objective(sample, data):
        x, y, xv, yv = data
        w = int(sample["width"])
        net = compile_model(
            get_model(
                "mlp_masked",
                features=(width_bucket(w, BUCKETS),),
                active=(w,),
                num_classes=10,
            ),
            optimizer={"name": "adam", "learning_rate": sample["lr"],
                       "injected": True},
            loss="categorical_crossentropy",
            metrics=["acc"],
            input_shape=x.shape[1:],
        )
        # respect the trial worker's pinned device (published thread-local
        # by HyperParamModel's worker threads)
        mesh = build_mesh(num_data=1, devices=[current_trial_device()])
        trainer = SyncTrainer(net, mesh, frequency="batch")
        state, history = trainer.fit(
            ShardedDataset(x, y, 1), epochs=1 if quick else 2, batch_size=64
        )
        val = trainer.evaluate_state(state, xv, yv)
        return {"loss": float(val["loss"]), "val_acc": float(val["acc"])}

    model = HyperParamModel(None)
    # 16 full-run trials over 2 bucket shapes: >= 14 land on warm
    # executables, giving the steady-state window a real sample
    # (VERDICT r4 #6 asks >= 12 steady trials).
    max_evals = 2 if quick else 16
    t0 = time.perf_counter()
    best = model.minimize(
        objective,
        lambda: (x, y, xv, yv),
        max_evals=max_evals,
        space={"lr": hp.loguniform(np.log(1e-4), np.log(1e-2)), "width": hp.choice([64, 128, 256])},
    )
    secs = time.perf_counter() - t0
    history = {"val_acc": [best["val_acc"]]}
    epochs_per_trial = 1 if quick else 2
    rec = _record(
        "hyperparam_search", "trial-parallel", history, n * max_evals,
        epochs_per_trial, secs, real,
        extra={"best_sample": best["sample"], "trials": max_evals},
    )
    # Steady-state trial throughput (VERDICT r3 #5, closing r2 weak #1's
    # last row): a trial pays full XLA compilation the first time its
    # worker sees a given model SHAPE — now the width BUCKET, since
    # masked widths within a bucket share the executable — so the
    # comparable rate excludes each worker's first occurrence of each
    # bucket (which subsumes the first trial). Per-trial timestamps come
    # from HyperParamModel itself.
    seen_shapes = set()
    steady = []
    for t in sorted(model.trials, key=lambda t: (t["worker"], t["trial"])):
        key = (t["worker"], width_bucket(int(t["sample"]["width"]), BUCKETS))
        if key in seen_shapes:
            steady.append(t)
        else:
            seen_shapes.add(key)
    if steady:
        span = max(t["t_end"] for t in steady) - min(t["t_start"] for t in steady)
        rec["samples_per_sec"] = round(
            n * epochs_per_trial * len(steady) / span, 2
        )
        rec["timing"] = "steady_state"
        rec["trials_per_sec_steady"] = round(len(steady) / span, 4)
        rec["steady_trials"] = len(steady)
        rec["warmup_trials"] = len(model.trials) - len(steady)
    return rec


CONFIGS = {
    "mnist_mlp_sync": mnist_mlp_sync,
    "mnist_cnn_async": mnist_cnn_async,
    "imdb_lstm_estimator": imdb_lstm_estimator,
    "cifar10_resnet18_hogwild": cifar10_resnet18_hogwild,
    "hyperparam_search": hyperparam_search,
}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true", help="small slices (smoke)")
    parser.add_argument("--configs", default=",".join(CONFIGS))
    parser.add_argument("--out", default="parity_results.jsonl")
    args = parser.parse_args()

    names = [n.strip() for n in args.configs.split(",") if n.strip()]
    unknown = set(names) - set(CONFIGS)
    if unknown:
        raise SystemExit(f"unknown configs: {sorted(unknown)}; known: {sorted(CONFIGS)}")

    control = round(micro_control(), 2)
    print(json.dumps({"control_samples_per_sec": control}), flush=True)

    records = []
    for name in names:
        rec = CONFIGS[name](args.quick)
        rec["control_samples_per_sec"] = control
        if rec.get("samples_per_sec"):
            rec["ratio_to_control"] = round(rec["samples_per_sec"] / control, 4)
        records.append(rec)
        print(json.dumps(rec), flush=True)
    with open(args.out, "a") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
