"""BASELINE config 2: MNIST CNN via SparkModel (asynchronous Downpour SGD).

Real MNIST when cached (``elephas_tpu.data.datasets``), synthetic
otherwise; asserts a validation threshold so it doubles as a smoke test.
"""

import os
import sys

# Runnable as `python examples/<name>.py` from anywhere: the package
# lives one level up from this file, not on the default sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

from elephas_tpu import SparkModel, compile_model, to_simple_rdd
from elephas_tpu.data.datasets import load_mnist, one_hot
from elephas_tpu.models import get_model


def main():
    (xtr, ytr), (xte, yte), real = load_mnist()
    x = (xtr.astype(np.float32) / 255.0)[..., None]  # NHWC
    y = one_hot(ytr, 10)
    xv = (xte.astype(np.float32) / 255.0)[..., None]
    yv = one_hot(yte, 10)
    net = compile_model(
        get_model("cnn", channels=(32, 64), dense_width=128, num_classes=10),
        optimizer={"name": "adam", "learning_rate": 1e-3},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=x.shape[1:],
    )
    n_workers = min(4, len(jax.devices()))
    model = SparkModel(
        net,
        mode="asynchronous",      # Downpour SGD
        frequency="epoch",        # pull/push once per local epoch
        parameter_server_mode="local",  # HBM buffer; 'http'/'socket' for multi-host
        num_workers=n_workers,
    )
    history = model.fit(
        to_simple_rdd(None, x, y, n_workers), epochs=3, batch_size=64,
        validation_data=(xv, yv), verbose=1,
    )
    print("final:", {k: round(v[-1], 4) for k, v in history.items()}, "real_data:", real)

    val_acc = history["val_acc"][-1]
    # Label-noise-capped synthetic (~0.89 Bayes); parity runs ~0.90.
    assert val_acc > 0.8, f"MNIST CNN async regressed: val_acc={val_acc:.3f} <= 0.8"


if __name__ == "__main__":
    main()
