"""BASELINE config 2: MNIST CNN via SparkModel (asynchronous Downpour SGD)."""

import numpy as np

from elephas_tpu import SparkModel, compile_model, to_simple_rdd
from elephas_tpu.models import get_model


def synthetic_mnist_images(n=8192, seed=0):
    rng = np.random.default_rng(seed)
    prototypes = rng.normal(scale=2.0, size=(10, 28, 28, 1))
    labels = rng.integers(0, 10, size=n)
    x = prototypes[labels] + rng.normal(size=(n, 28, 28, 1))
    return x.astype(np.float32), np.eye(10, dtype=np.float32)[labels]


def main():
    x, y = synthetic_mnist_images()
    net = compile_model(
        get_model("cnn", channels=(32, 64), dense_width=128, num_classes=10),
        optimizer={"name": "adam", "learning_rate": 1e-3},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=(28, 28, 1),
    )
    model = SparkModel(
        net,
        mode="asynchronous",      # Downpour SGD
        frequency="epoch",        # pull/push once per local epoch
        parameter_server_mode="local",  # HBM-resident buffer; 'http'/'socket' for multi-host
        num_workers=4,
    )
    history = model.fit(to_simple_rdd(None, x, y, 4), epochs=5, batch_size=64, verbose=1)
    print("eval:", model.evaluate(x, y))


if __name__ == "__main__":
    main()
