"""Elastic ASHA hyperparameter search over the worker pool.

The successor to ``hyperparam_search.py``'s fan-out-and-argmin: instead
of giving every sampled config the full epoch budget, ``tune.run_search``
runs trials as lease-fenced units on the elastic pool and promotes only
the top 1/eta of each rung — most configs are pruned after one epoch,
and the budget concentrates on the survivors. The trial function is
*resumable*: it trains ``epochs`` MORE epochs from ``state`` (the
model's parameter pytree, checkpointed in the tuner's vault), so a
promoted — or re-leased — trial continues instead of restarting.
"""

import os
import sys

# Runnable as `python examples/<name>.py` from anywhere: the package
# lives one level up from this file, not on the default sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from elephas_tpu import SparkModel, compile_model, hp, to_simple_rdd
from elephas_tpu.models import get_model
from elephas_tpu.tune import run_search


def data():
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=3.0, size=(4, 20))
    labels = rng.integers(0, 4, size=2048)
    x = (centers[labels] + rng.normal(size=(2048, 20))).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[labels]
    return x[:1536], y[:1536], x[1536:], y[1536:]


SPACE = {
    "lr": hp.loguniform(np.log(1e-4), np.log(1e-1)),
    "width": hp.choice([32, 64, 128]),
    "batch_size": hp.choice([32, 64]),
}

X, Y, XV, YV = data()


def trial_fn(config, state, epochs, seed, rung):
    """Train ``epochs`` more epochs from ``state`` (None = fresh init)
    and report the validation loss — the rung score ASHA ranks."""
    net = compile_model(
        get_model("mlp", features=(config["width"],), num_classes=4),
        optimizer={"name": "adam", "learning_rate": config["lr"]},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=(20,),
    )
    if state is not None:
        net.set_weights(state)
    model = SparkModel(net, mode="asynchronous", frequency="epoch",
                       parameter_server_mode="local", num_workers=1)
    model.fit(to_simple_rdd(None, X, Y, 1), epochs=int(epochs),
              batch_size=int(config["batch_size"]), verbose=0)
    val = model.evaluate(XV, YV)
    return {"loss": float(val["loss"]), "state": net.get_weights(),
            "val_acc": float(val["acc"])}


def main():
    doc = run_search(trial_fn, SPACE, num_trials=9, seed=0,
                     eta=3, rungs=3, r0=1, workers=2)
    winner = doc["winner"]
    saved = 1.0 - doc["epochs_spent"] / doc["full_budget_epochs"]
    print("winner config:", winner["config"])
    print(f"best val loss: {doc['best_loss']:.4f}  "
          f"(digest {doc['winner_digest']})")
    print(f"epochs: {doc['epochs_spent']} spent vs "
          f"{doc['full_budget_epochs']} full budget ({saved:.0%} saved)")
    print("counts:", doc["counts"])

    assert doc["lost_trials"] == 0, "search lost trials"
    assert saved > 0.4, (
        f"ASHA pruning regressed: only {saved:.0%} of the full budget saved"
    )


if __name__ == "__main__":
    main()
