"""BASELINE config 5: distributed hyperparameter search across chips.

Reference workflow (§3.4): independent trials fan out, one stream per
worker, driver takes the argmin. Search space uses the hp combinators
(the hyperas/hyperopt analogue).
"""

import os
import sys

# Runnable as `python examples/<name>.py` from anywhere: the package
# lives one level up from this file, not on the default sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from elephas_tpu import HyperParamModel, SparkModel, compile_model, hp, to_simple_rdd
from elephas_tpu.models import get_model


def data():
    rng = np.random.default_rng(0)
    centers = rng.normal(scale=3.0, size=(4, 20))
    labels = rng.integers(0, 4, size=2048)
    x = (centers[labels] + rng.normal(size=(2048, 20))).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[labels]
    return x[:1536], y[:1536], x[1536:], y[1536:]


SPACE = {
    "lr": hp.loguniform(np.log(1e-4), np.log(1e-1)),
    "width": hp.choice([32, 64, 128]),
    "batch_size": hp.choice([32, 64]),
}


def objective(sample, dataset):
    x, y, xv, yv = dataset
    net = compile_model(
        get_model("mlp", features=(sample["width"],), num_classes=4),
        optimizer={"name": "adam", "learning_rate": sample["lr"]},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=(20,),
    )
    model = SparkModel(net, mode="synchronous", frequency="batch", num_workers=1)
    model.fit(to_simple_rdd(None, x, y, 1), epochs=3, batch_size=sample["batch_size"])
    val = model.evaluate(xv, yv)
    return {"loss": val["loss"], "model": net, "val_acc": val["acc"]}


def main():
    search = HyperParamModel(None, num_workers=4)
    best = search.minimize(objective, data, max_evals=8, space=SPACE, seed=0)
    print("best sample:", best["sample"], "val_acc:", round(best["val_acc"], 4))

    assert best["val_acc"] > 0.85, (
        f"hyperparam search regressed: best val_acc={best['val_acc']:.3f} <= 0.85"
    )


if __name__ == "__main__":
    main()
