"""Long-context LM training with sequence parallelism (beyond the reference).

Trains a small decoder-only ``TransformerLM`` over a dp×sp mesh with the
sequence sharded across chips — both layouts:

- ``attention='ring'``: K/V shards rotate with ``lax.ppermute``; each
  hop runs through the Pallas flash kernels at ≥2k tokens/shard.
- ``attention='ulysses'``: one stacked all-to-all re-shards seq↔heads,
  full-length flash attention runs per head subset, one all-to-all back.

No analogue exists in the reference (SURVEY.md §5.7 — its longest
sequence is an IMDB LSTM at a few hundred tokens). Runs on any device
count: the mesh shapes itself to what's available (8 virtual CPU devices
under the test harness, a v5e slice in production). Ends with threshold
asserts so it doubles as a smoke test (SURVEY.md §4).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    from elephas_tpu import compile_model
    from elephas_tpu.models import get_model
    from elephas_tpu.parallel.mesh import build_mesh
    from elephas_tpu.parallel.seq_parallel import (
        init_lm_state,
        make_lm_train_step,
        shard_lm_batch,
    )

    n = len(jax.devices())
    num_seq = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
    num_data = max(1, n // num_seq)
    seq, vocab = 128, 256
    batch = num_data * 4  # batch dim must divide over the 'data' axis

    # Synthetic second-order corpus: token i = token[i-1] + token[i-2]
    # (mod vocab) — a true sequential recurrence a causal LM can learn,
    # so loss visibly falls.
    rng = np.random.default_rng(0)
    base = rng.integers(0, vocab, size=(batch, seq + 1)).astype(np.int32)
    for i in range(2, seq + 1):
        base[:, i] = (base[:, i - 1] + base[:, i - 2]) % vocab
    tokens_np, targets_np = base[:, :-1], base[:, 1:]

    losses = {}
    for attention in ("ring", "ulysses"):
        net = compile_model(
            get_model(
                "transformer_lm",
                vocab_size=vocab,
                d_model=64,
                num_heads=4,
                num_layers=2,
                max_seq_len=seq,
                attention=attention,
            ),
            optimizer={"name": "adam", "learning_rate": 3e-3},
            loss="sparse_categorical_crossentropy",
            input_shape=(seq,),
            input_dtype="int32",
        )
        mesh = build_mesh(num_data=num_data, num_seq=num_seq)
        step = make_lm_train_step(net, mesh)
        state = init_lm_state(net, mesh)
        tokens, targets = shard_lm_batch(mesh, tokens_np, targets_np)
        history = []
        for _ in range(30):
            state, metrics = step(state, tokens, targets)
            history.append(float(metrics["loss"]))
        losses[attention] = history
        net_sp, state_sp = net, state  # kept for the generation demo below
        print(
            f"[{attention}] mesh data={num_data} seq={num_seq} "
            f"loss {history[0]:.3f} -> {history[-1]:.3f}"
        )

    for attention, history in losses.items():
        assert history[-1] < history[0] * 0.7, (
            f"{attention} LM failed to learn: {history[0]:.3f} -> {history[-1]:.3f}"
        )
    # Both layouts are exact attention over the same init: first-step
    # losses must agree tightly.
    np.testing.assert_allclose(
        losses["ring"][0], losses["ulysses"][0], rtol=1e-3
    )
    print("ok: both sequence-parallel layouts learn and agree at step 1")

    # Fit-shaped driver (SeqParallelTrainer): SparkModel.fit ergonomics
    # for long context — shuffled epochs, validation, history — with
    # attention='auto' picking the layout from the topology.
    from elephas_tpu.parallel.seq_parallel import SeqParallelTrainer

    corpus = rng.integers(0, vocab, size=(batch * 4, seq + 1)).astype(np.int32)
    for i in range(2, seq + 1):
        corpus[:, i] = (corpus[:, i - 1] + corpus[:, i - 2]) % vocab
    net = compile_model(
        get_model("transformer_lm", vocab_size=vocab, d_model=64,
                  num_heads=4, num_layers=2, max_seq_len=seq,
                  attention="auto"),
        optimizer={"name": "adam", "learning_rate": 3e-3},
        loss="sparse_categorical_crossentropy",
        input_shape=(seq,),
        input_dtype="int32",
    )
    trainer = SeqParallelTrainer(
        net, build_mesh(num_data=num_data, num_seq=num_seq)
    )
    state, history = trainer.fit(
        corpus, epochs=10, batch_size=batch,
        validation_tokens=corpus[: batch],
    )
    assert history["loss"][-1] < history["loss"][0] * 0.7
    assert len(history["val_loss"]) == 10
    print(
        f"ok: SeqParallelTrainer(auto) fit {history['loss'][0]:.3f} -> "
        f"{history['loss'][-1]:.3f} (val {history['val_loss'][-1]:.3f})"
    )

    # Inference: sample from the sequence-parallel-trained model through
    # the KV-cache decode path (batched prefill + one forward per token).
    # Greedy continuation only follows the recurrence once the model has
    # MEMORIZED its batch — guard on the loss explicitly so a training
    # shortfall fails here, not inside the generation assert.
    assert losses["ulysses"][-1] < 1.0, losses["ulysses"][-1]
    from elephas_tpu.models.transformer import generate

    out = generate(net_sp, base[:2, :8], max_new_tokens=24,
                   params=state_sp.params)
    hits = sum(
        int(out[r, i] == (out[r, i - 1] + out[r, i - 2]) % vocab)
        for r in range(2)
        for i in range(8, out.shape[1])
    )
    total_checked = 2 * (out.shape[1] - 8)
    assert hits / total_checked > 0.7, f"{hits}/{total_checked}"
    print(f"ok: generate continues the recurrence {hits}/{total_checked}")


if __name__ == "__main__":
    main()
