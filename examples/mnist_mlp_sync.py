"""BASELINE config 1: MNIST MLP via SparkModel (synchronous, 4 partitions).

Mirrors the reference's ``examples/mnist_mlp_spark.py`` workflow. Data
comes from ``elephas_tpu.data.datasets.load_mnist``: the real MNIST when
``$ELEPHAS_DATA_DIR/mnist.npz`` exists, else a deterministic synthetic
stand-in. Ends with a threshold assert so it doubles as a smoke test
(SURVEY.md §4 "examples as smoke tests").
"""

import os
import sys

# Runnable as `python examples/<name>.py` from anywhere: the package
# lives one level up from this file, not on the default sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from elephas_tpu import SparkModel, compile_model, to_simple_rdd
from elephas_tpu.data.datasets import load_mnist, one_hot
from elephas_tpu.models import get_model


def main():
    (xtr, ytr), (xte, yte), real = load_mnist()
    x, y = xtr.astype(np.float32) / 255.0, one_hot(ytr, 10)
    xv, yv = xte.astype(np.float32) / 255.0, one_hot(yte, 10)
    net = compile_model(
        get_model("mlp", features=(128, 128), num_classes=10, dropout_rate=0.1),
        optimizer={"name": "adam", "learning_rate": 1e-3},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=x.shape[1:],
    )
    model = SparkModel(net, mode="synchronous", frequency="batch", num_workers=4)
    rdd = to_simple_rdd(None, x, y, num_partitions=4)
    history = model.fit(rdd, epochs=5, batch_size=32, validation_data=(xv, yv), verbose=1)
    print("final:", {k: round(v[-1], 4) for k, v in history.items()}, "real_data:", real)
    model.save("/tmp/mnist_mlp_sync.pkl")

    val_acc = history["val_acc"][-1]
    # Synthetic MNIST carries ~12% label noise (Bayes-optimal ~0.89);
    # full parity runs land ~0.90 — 0.8 keeps seed-to-seed margin.
    assert val_acc > 0.8, f"MNIST MLP sync regressed: val_acc={val_acc:.3f} <= 0.8"


if __name__ == "__main__":
    main()
