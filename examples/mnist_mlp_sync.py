"""BASELINE config 1: MNIST MLP via SparkModel (synchronous, 4 partitions).

Mirrors the reference's ``examples/mnist_mlp_spark.py`` workflow. The
environment has no network access, so data is synthetic MNIST-shaped
(28x28 grayscale, 10 classes); swap ``synthetic_mnist`` for a real loader
when one is available.
"""

import numpy as np

from elephas_tpu import SparkModel, compile_model, to_simple_rdd
from elephas_tpu.models import get_model


def synthetic_mnist(n=8192, seed=0):
    rng = np.random.default_rng(seed)
    prototypes = rng.normal(scale=2.0, size=(10, 28 * 28))
    labels = rng.integers(0, 10, size=n)
    x = prototypes[labels] + rng.normal(size=(n, 28 * 28))
    return x.astype(np.float32).reshape(n, 28, 28), np.eye(10, dtype=np.float32)[labels]


def main():
    x, y = synthetic_mnist()
    net = compile_model(
        get_model("mlp", features=(128, 128), num_classes=10, dropout_rate=0.1),
        optimizer={"name": "adam", "learning_rate": 1e-3},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=(28, 28),
    )
    model = SparkModel(net, mode="synchronous", frequency="batch", num_workers=4)
    rdd = to_simple_rdd(None, x, y, num_partitions=4)
    history = model.fit(rdd, epochs=5, batch_size=32, validation_split=0.1, verbose=1)
    print("final:", {k: round(v[-1], 4) for k, v in history.items()})
    model.save("/tmp/mnist_mlp_sync.pkl")


if __name__ == "__main__":
    main()
