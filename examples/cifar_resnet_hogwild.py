"""BASELINE config 4: CIFAR-10 ResNet-18, mode=hogwild (the primary
benchmark workload — see bench.py for the throughput harness)."""

import numpy as np

from elephas_tpu import SparkModel, compile_model, to_simple_rdd
from elephas_tpu.models import get_model


def synthetic_cifar(n=4096, seed=0):
    rng = np.random.default_rng(seed)
    prototypes = rng.normal(scale=1.5, size=(10, 32, 32, 3))
    labels = rng.integers(0, 10, size=n)
    x = prototypes[labels] + rng.normal(size=(n, 32, 32, 3))
    return x.astype(np.float32), np.eye(10, dtype=np.float32)[labels]


def main():
    x, y = synthetic_cifar()
    net = compile_model(
        get_model("resnet18", num_classes=10, dtype="bfloat16"),
        optimizer={"name": "momentum", "learning_rate": 0.1},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=(32, 32, 3),
    )
    model = SparkModel(
        net,
        mode="hogwild",           # lock-free Downpour (Hogwild!)
        frequency="epoch",
        parameter_server_mode="local",
        num_workers=4,
    )
    history = model.fit(to_simple_rdd(None, x, y, 4), epochs=3, batch_size=128, verbose=1)
    print("eval:", model.evaluate(x, y, batch_size=512))


if __name__ == "__main__":
    main()
