"""BASELINE config 4: CIFAR-10 ResNet-18, mode=hogwild (the primary
benchmark workload — see bench.py for the throughput harness).

Real CIFAR-10 when cached (``elephas_tpu.data.datasets``), synthetic
otherwise; asserts a validation threshold so it doubles as a smoke test.
"""

import os
import sys

# Runnable as `python examples/<name>.py` from anywhere: the package
# lives one level up from this file, not on the default sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

from elephas_tpu import SparkModel, compile_model, to_simple_rdd
from elephas_tpu.data.datasets import load_cifar10, one_hot
from elephas_tpu.models import get_model


def main():
    (xtr, ytr), (xte, yte), real = load_cifar10()
    mean = np.array([0.4914, 0.4822, 0.4465], np.float32) * 255.0
    std = np.array([0.247, 0.243, 0.261], np.float32) * 255.0
    x, y = (xtr.astype(np.float32) - mean) / std, one_hot(ytr, 10)
    xv, yv = (xte.astype(np.float32) - mean) / std, one_hot(yte, 10)
    net = compile_model(
        get_model("resnet18", num_classes=10, dtype="bfloat16"),
        optimizer={"name": "momentum", "learning_rate": 0.05},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=(32, 32, 3),
    )
    n_workers = min(4, len(jax.devices()))
    model = SparkModel(
        net,
        mode="hogwild",           # lock-free Downpour (Hogwild!)
        frequency="epoch",
        parameter_server_mode="local",
        num_workers=n_workers,
    )
    history = model.fit(
        to_simple_rdd(None, x, y, n_workers), epochs=3, batch_size=128,
        validation_data=(xv, yv), verbose=1,
    )
    print("final:", {k: round(v[-1], 4) for k, v in history.items()}, "real_data:", real)

    val_acc = history["val_acc"][-1]
    # Label-noise-capped synthetic (~0.89 Bayes); 3-epoch runs land ~0.8.
    assert val_acc > 0.6, f"CIFAR ResNet hogwild regressed: val_acc={val_acc:.3f} <= 0.6"


if __name__ == "__main__":
    main()
