"""BASELINE config 3: IMDB LSTM via the ElephasEstimator pipeline.

Reference workflow (§3.3): DataFrame -> Estimator.fit -> Transformer ->
DataFrame with predictions. Synthetic IMDB-shaped data: token sequences
(vocab 2000, len 100), binary sentiment driven by planted token stats.
"""

import numpy as np

from elephas_tpu import ElephasEstimator
from elephas_tpu.data.dataframe import DataFrame


def synthetic_imdb(n=2048, vocab=2000, seq_len=100, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 2, size=n)
    # Positive reviews skew toward the upper half of the vocab.
    low = rng.integers(1, vocab // 2, size=(n, seq_len))
    high = rng.integers(vocab // 2, vocab, size=(n, seq_len))
    mask = rng.random((n, seq_len)) < (0.35 + 0.3 * labels)[:, None]
    tokens = np.where(mask, high, low).astype(np.int32)
    return tokens, labels.astype(np.float32)


def main():
    tokens, labels = synthetic_imdb()
    df = DataFrame({"features": tokens, "label": labels})

    estimator = ElephasEstimator(
        keras_model_config={
            "name": "lstm",
            "kwargs": {"vocab_size": 2000, "embed_dim": 64, "hidden_dim": 64,
                        "num_classes": 2},
            "input_shape": (100,),
            "input_dtype": "int32",
        },
        mode="synchronous",
        frequency="batch",
        nb_classes=2,
        num_workers=4,
        epochs=3,
        batch_size=32,
        optimizer_config={"name": "adam", "learning_rate": 1e-3},
        loss="categorical_crossentropy",
        metrics=("acc",),
        categorical=True,
    )
    transformer = estimator.fit(df)
    out = transformer.transform(df)
    acc = float(np.mean(out["prediction"] == df["label"]))
    print(f"pipeline accuracy: {acc:.3f}")
    transformer.save("/tmp/imdb_lstm_transformer.pkl")


if __name__ == "__main__":
    main()
