"""BASELINE config 3: IMDB LSTM via the ElephasEstimator pipeline.

Reference workflow (§3.3): DataFrame -> Estimator.fit -> Transformer ->
DataFrame with predictions. Real IMDB when cached
(``elephas_tpu.data.datasets``), synthetic token sequences otherwise;
asserts a held-out accuracy threshold so it doubles as a smoke test.
"""

import os
import sys

# Runnable as `python examples/<name>.py` from anywhere: the package
# lives one level up from this file, not on the default sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from elephas_tpu import ElephasEstimator
from elephas_tpu.data.dataframe import DataFrame
from elephas_tpu.data.datasets import load_imdb

MAXLEN = 200
VOCAB = 20000


def main():
    (xtr, ytr), (xte, yte), real = load_imdb(num_words=VOCAB, maxlen=MAXLEN)
    df = DataFrame({"features": xtr.astype(np.int32), "label": ytr.astype(np.float32)})

    estimator = ElephasEstimator(
        keras_model_config={
            "name": "lstm",
            "kwargs": {"vocab_size": VOCAB, "embed_dim": 64, "hidden_dim": 64,
                        "num_classes": 2},
            "input_shape": (MAXLEN,),
            "input_dtype": "int32",
        },
        mode="synchronous",
        frequency="batch",
        nb_classes=2,
        num_workers=4,
        epochs=3,
        batch_size=32,
        optimizer_config={"name": "adam", "learning_rate": 1e-3},
        loss="categorical_crossentropy",
        metrics=("acc",),
        categorical=True,
    )
    transformer = estimator.fit(df)
    test_df = DataFrame(
        {"features": xte.astype(np.int32), "label": yte.astype(np.float32)}
    )
    out = transformer.transform(test_df)
    acc = float(np.mean(out["prediction"] == test_df["label"]))
    print(f"pipeline held-out accuracy: {acc:.3f} (real_data: {real})")
    transformer.save("/tmp/imdb_lstm_transformer.pkl")

    assert acc > 0.7, f"IMDB LSTM estimator regressed: held-out acc={acc:.3f} <= 0.7"


if __name__ == "__main__":
    main()
