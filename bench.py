"""Primary benchmark: CIFAR-10 ResNet-18 samples/sec/chip (BASELINE.md).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "samples/sec/chip", "vs_baseline": N}

``vs_baseline`` is measured against a per-worker CPU train-step baseline
(the stand-in for the reference's TF-CPU Spark workers — BASELINE.json's
"TF-CPU Spark baseline"; no published numbers exist, SURVEY.md §6).
The CPU rate is measured once in a subprocess and cached in
``.bench_cpu_baseline.json`` so repeat runs are fast.

All diagnostics go to stderr; stdout carries only the JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
CACHE = os.path.join(REPO, ".bench_cpu_baseline.json")

BATCH_TPU = 2048  # sweep-selected: +5% over 512 at bf16 norms (PROFILE.md §1)
BATCH_CPU = 64
WARMUP = 5
MEASURE = 50


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def measure_train_rate(batch_size: int, steps: int, warmup: int, dtype: str) -> float:
    """samples/sec of the jitted ResNet-18 train step on the default backend."""
    import jax
    import numpy as np

    from elephas_tpu.api.compile import CompiledModel
    from elephas_tpu.engine.step import init_train_state, make_train_step
    from elephas_tpu.models import get_model

    module = get_model("resnet18", num_classes=10, width=64, dtype=dtype)
    compiled = CompiledModel(
        module,
        optimizer={"name": "momentum", "learning_rate": 0.1},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=(32, 32, 3),
    )
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch_size, 32, 32, 3)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch_size)]
    # Pin everything to ONE device: the metric is samples/sec/chip, so the
    # measurement itself must be single-chip even on a multi-chip host.
    # Inputs stay float32 — what the shipped trainers actually feed
    # (sweeps showed bf16 input is within noise anyway, PROFILE.md §1).
    device = jax.devices()[0]
    x, y = jax.device_put(x, device), jax.device_put(y, device)
    state = jax.device_put(init_train_state(compiled), device)

    from elephas_tpu.utils.compiler import autotune_compile_options

    # Per-workload compile-option A/B (VERDICT r4 #5) — the same
    # autotune the trainers run under ``autotune=True``: the scoped-VMEM
    # knob measured +4–5% on exactly this bare conv step but −43% on the
    # LSTM fit (utils/compiler.py table), so a measurement, not a
    # default, picks the options. $ELEPHAS_SCOPED_VMEM_KIB still forces
    # a choice (the candidate list collapses to it). The A/B arms are
    # undonated (each dispatch reuses ``state``); only the measured step
    # donates.
    def _build(opts):
        return jax.jit(make_train_step(compiled), compiler_options=opts)

    winner, opts, table = autotune_compile_options(
        _build,
        lambda fn: fn(state, x, y),
        lambda out: float(out[1]["loss"]),
    )
    if table:
        log(f"compile autotune: {winner} wins — "
            + ", ".join(f"{k}={v:.2f}ms" for k, v in table.items()))
    step = jax.jit(
        make_train_step(compiled), donate_argnums=(0,),
        compiler_options=opts,
    )
    for _ in range(warmup):
        state, metrics = step(state, x, y)
    # Anchor on a value fetch, not block_until_ready: remote-tunneled TPU
    # backends (axon) have been observed to return from block_until_ready
    # without the execution chain having finished, inflating rates past
    # the chip's peak FLOPs. Fetching the scalar loss forces the chain.
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, x, y)
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    return batch_size * steps / dt


CPU_STEPS = 20  # ≥20 measured steps (VERDICT r2 #7); two runs, variance-checked


def cpu_baseline_rate() -> float:
    """Per-worker CPU train-step rate — the stand-in for the reference's
    TF-CPU Spark executor (an approximation: same model/batch, JAX-CPU
    instead of TF-CPU). Measured over two independent ``CPU_STEPS``-step
    runs in one subprocess; cached with the run-to-run spread recorded.
    """
    if os.path.exists(CACHE):
        with open(CACHE) as f:
            cached = json.load(f)
        # Only trust caches produced by the current methodology — a stale
        # record from the old single 3-step run would silently keep the
        # noisy baseline this measurement replaced.
        if cached.get("steps") == CPU_STEPS and len(cached.get("runs", [])) >= 2:
            return cached["samples_per_sec"]
        log("stale CPU baseline cache (old methodology); re-measuring")
    log("measuring CPU per-worker baseline (one-time, cached)...")
    code = (
        "import jax, json, sys;"
        "jax.config.update('jax_platforms','cpu');"
        "sys.path.insert(0, %r);"
        "from bench import measure_train_rate;"
        "rates=[measure_train_rate(%d, %d, 2, 'float32') for _ in range(2)];"
        "print(json.dumps(rates))" % (REPO, BATCH_CPU, CPU_STEPS)
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=1800,
        cwd=REPO,
    )
    if out.returncode != 0:
        log("CPU baseline failed:", out.stderr[-2000:])
        raise RuntimeError("cpu baseline subprocess failed")
    rates = json.loads(out.stdout.strip().splitlines()[-1])
    rate = sum(rates) / len(rates)
    spread = abs(rates[0] - rates[1]) / rate
    if spread > 0.10:
        log(f"warning: CPU baseline runs differ by {spread:.1%}: {rates}")
    with open(CACHE, "w") as f:
        json.dump(
            {
                "samples_per_sec": rate,
                "batch": BATCH_CPU,
                "steps": CPU_STEPS,
                "runs": rates,
                "rel_spread": round(spread, 4),
            },
            f,
        )
    return rate


def main() -> None:
    import jax

    backend = jax.default_backend()
    log(f"backend={backend} devices={jax.devices()}")
    dtype = "bfloat16" if backend == "tpu" else "float32"
    batch = BATCH_TPU if backend == "tpu" else BATCH_CPU
    # measure_train_rate pins to a single chip, so its rate IS per-chip.
    per_chip = measure_train_rate(batch, MEASURE, WARMUP, dtype)
    log(f"single-chip train rate: {per_chip:.1f} samples/sec")

    try:
        baseline = cpu_baseline_rate()
        vs = per_chip / baseline
        log(f"cpu per-worker baseline: {baseline:.2f} samples/sec -> {vs:.1f}x")
    except Exception as exc:  # baseline is informative, not load-bearing
        log("baseline unavailable:", exc)
        vs = 0.0

    print(
        json.dumps(
            {
                "metric": "cifar10_resnet18_train_throughput",
                "value": round(per_chip, 2),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
