"""Distributed hyperparameter search — compatibility façade.

The implementation moved to ``elephas_tpu/tune/`` (search.py carries
the ``hp`` combinators, samplers, and ``HyperParamModel`` verbatim;
scheduler/runner/vault add the elastic ASHA frontend). This module
stays importable forever: it is the reference-parity path
(``elephas/hyperparam.py::HyperParamModel``, SURVEY.md §2.1/§3.4) that
existing code and the r1–r5 parity harnesses import from.

New code should prefer ``elephas_tpu.tune`` — same ``hp`` spaces, plus
``run_search`` for kill-safe successive-halving searches on the
elastic worker pool.
"""

from __future__ import annotations

from elephas_tpu.tune.search import (  # noqa: F401
    HyperParamModel,
    _SAMPLERS,
    _Choice,
    _Dist,
    _LogUniform,
    _QUniform,
    _RandInt,
    _RandomSampler,
    _TPESampler,
    _Uniform,
    _iter_nodes,
    _substitute,
    _trial_ctx,
    current_trial_device,
    hp,
    sample_space,
    width_bucket,
)

__all__ = [
    "hp", "HyperParamModel", "sample_space", "current_trial_device",
    "width_bucket",
]
