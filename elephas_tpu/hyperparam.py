"""Distributed hyperparameter search.

Reference: ``elephas/hyperparam.py::HyperParamModel`` (SURVEY.md §2.1,
§3.4): hyperas parses a templated model function, ``sc.parallelize``
fans independent ``hyperopt.fmin`` runs out across executors — *search-
space partitioning*, not coordinated Bayesian optimization (each worker
keeps its own ``Trials()``), and the driver picks the argmin.

TPU-native redesign: hyperas/hyperopt don't exist here, so the search
space is declared with the ``hp`` combinators below and the objective is
a plain callable. Trials stay embarrassingly parallel with *independent
per-worker streams* (the reference's exact semantic, including its
limitation — documented, not "fixed"): one host thread per chip, each
thread pinning its trials to its device via ``jax.default_device``. On
multi-host pods each host runs its own ``HyperParamModel`` over its
local chips (SURVEY.md §7 step 6).

Objective contract (hyperopt-compatible):
    ``model_fn(sample: dict, data) -> {"loss": float, "model": CompiledModel,
    "status": "ok"}``  — extra keys are kept and returned with the trial.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

__all__ = ["hp", "HyperParamModel", "sample_space"]


class _Dist:
    def sample(self, rng: np.random.Generator):
        raise NotImplementedError


class _Choice(_Dist):
    def __init__(self, options):
        self.options = list(options)

    def sample(self, rng):
        return self.options[rng.integers(len(self.options))]


class _Uniform(_Dist):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))


class _LogUniform(_Dist):
    def __init__(self, low, high):
        # hyperopt convention: bounds are on log(value).
        self.low, self.high = low, high

    def sample(self, rng):
        return float(np.exp(rng.uniform(self.low, self.high)))


class _QUniform(_Dist):
    def __init__(self, low, high, q):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        return float(np.round(rng.uniform(self.low, self.high) / self.q) * self.q)


class _RandInt(_Dist):
    def __init__(self, upper):
        self.upper = upper

    def sample(self, rng):
        return int(rng.integers(self.upper))


class hp:
    """hyperopt-flavored search-space combinators."""

    choice = _Choice
    uniform = _Uniform
    loguniform = _LogUniform
    quniform = _QUniform
    randint = _RandInt


def sample_space(space: Any, rng: np.random.Generator) -> Any:
    """Recursively sample every ``hp.*`` node in a nested dict/list/tuple."""
    if isinstance(space, _Dist):
        return space.sample(rng)
    if isinstance(space, dict):
        return {k: sample_space(v, rng) for k, v in space.items()}
    if isinstance(space, (list, tuple)):
        return type(space)(sample_space(v, rng) for v in space)
    return space


class HyperParamModel:
    """Distributed random search with per-worker independent streams.

    Constructor mirrors the reference (``HyperParamModel(sc, num_workers)``);
    ``sc`` is accepted-and-ignored (no Spark driver).
    """

    def __init__(self, sc=None, num_workers: Optional[int] = None):
        del sc
        n_devices = len(jax.devices())
        self.num_workers = min(num_workers or n_devices, n_devices)
        self.best_models: List[Dict] = []  # per-worker bests (reference attr)

    def minimize(
        self,
        model: Callable,
        data: Callable,
        max_evals: int = 10,
        space: Optional[Dict] = None,
        seed: int = 0,
    ):
        """Run ``max_evals`` trials split across workers; return the best
        trial dict (``{"loss", "model", "sample", ...}``).

        ``model``: objective ``(sample, data) -> {"loss", "model", ...}``.
        ``data``: zero-arg callable returning the dataset given to every
        trial (the reference's hyperas ``data`` function).
        """
        if space is None:
            space = {}
        dataset = data() if callable(data) else data
        # Exactly max_evals trials total: worker i takes the remainder's
        # i-th extra trial (idle workers get zero).
        base, extra = divmod(max_evals, self.num_workers)
        trials_for = [base + (1 if i < extra else 0) for i in range(self.num_workers)]
        devices = jax.devices()[: self.num_workers]
        results: List[List[Dict]] = [[] for _ in range(self.num_workers)]
        errors: List[BaseException] = []

        def worker(index: int, device) -> None:
            # Independent stream per worker — the reference's independent
            # Trials() semantics (§3.4 note).
            # SeedSequence spawning: collision-free across (seed, worker)
            # pairs, unlike arithmetic seed mixing.
            rng = np.random.default_rng([seed, index])
            try:
                with jax.default_device(device):
                    for trial in range(trials_for[index]):
                        sample = sample_space(space, rng)
                        out = model(sample, dataset)
                        if not isinstance(out, dict) or "loss" not in out:
                            raise TypeError(
                                "objective must return a dict with a 'loss' key"
                            )
                        out.setdefault("status", "ok")
                        out["sample"] = sample
                        out["worker"] = index
                        out["trial"] = trial
                        results[index].append(out)
            except BaseException as exc:
                errors.append(exc)

        threads = [
            threading.Thread(target=worker, args=(i, dev), daemon=True)
            for i, dev in enumerate(devices)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

        self.best_models = [
            min(worker_results, key=lambda r: r["loss"])
            for worker_results in results
            if worker_results
        ]
        if not self.best_models:
            raise RuntimeError("no trials completed")
        return min(self.best_models, key=lambda r: r["loss"])

    def best_model(self):
        """Best model object across workers (reference convenience)."""
        if not self.best_models:
            raise RuntimeError("call minimize() first")
        best = min(self.best_models, key=lambda r: r["loss"])
        return best.get("model")
