"""ResNet-18 (BASELINE config 4 / primary benchmark: CIFAR-10 hogwild).

TPU-first choices: NHWC layout (native for TPU convs), optional bfloat16
compute with float32 parameters/statistics (MXU-friendly mixed precision),
CIFAR-style 3x3 stem by default (the benchmark is CIFAR-10; ImageNet-style
7x7 stem + maxpool available via ``imagenet_stem=True``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from elephas_tpu.models import register_model


class ResidualBlock(nn.Module):
    channels: int
    strides: int = 1
    dtype: Any = jnp.float32
    norm_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        # norm_dtype sets the normalize/scale/shift output dtype only; flax
        # always computes the mean/var reductions and running stats in f32,
        # so norm_dtype=bf16 halves the elementwise HBM traffic without
        # touching statistics precision.
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.norm_dtype,
        )
        residual = x
        y = conv(self.channels, (3, 3), strides=(self.strides, self.strides), padding="SAME")(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.channels, (3, 3), padding="SAME")(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = conv(
                self.channels, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (2, 2, 2, 2)  # ResNet-18
    num_classes: int = 10
    width: int = 64
    dtype: Any = jnp.float32
    norm_dtype: Any = jnp.float32
    imagenet_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if self.imagenet_stem:
            x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                        use_bias=False, dtype=self.dtype)(x)
        else:
            x = nn.Conv(self.width, (3, 3), padding="SAME", use_bias=False,
                        dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5,
                         dtype=self.norm_dtype)(x)
        x = nn.relu(x)
        if self.imagenet_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, num_blocks in enumerate(self.stage_sizes):
            channels = self.width * (2**stage)
            for block in range(num_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = ResidualBlock(
                    channels, strides=strides, dtype=self.dtype,
                    norm_dtype=self.norm_dtype,
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        # Head in f32 for numerically-stable softmax.
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x.astype(jnp.float32))


def ResNet18(num_classes: int = 10, width: int = 64, dtype=jnp.float32,
             norm_dtype=None, imagenet_stem=False):
    return ResNet(
        stage_sizes=(2, 2, 2, 2),
        num_classes=num_classes,
        width=width,
        dtype=dtype,
        norm_dtype=dtype if norm_dtype is None else norm_dtype,
        imagenet_stem=imagenet_stem,
    )


@register_model("resnet18")
def build_resnet18(num_classes=10, width=64, dtype="float32", norm_dtype=None,
                   imagenet_stem=False):
    dtype = jnp.dtype(dtype)
    return ResNet18(
        num_classes=num_classes,
        width=width,
        dtype=dtype,
        norm_dtype=dtype if norm_dtype is None else jnp.dtype(norm_dtype),
        imagenet_stem=imagenet_stem,
    )
