"""Decoder-only transformer LM — the long-context flagship.

Not present in the reference (SURVEY.md §5.7: no long-context support
anywhere in elephas); included because long sequences are first-class in
the TPU rebuild. The attention implementation is pluggable:

- ``attention='dense'`` — plain softmax attention (XLA-fused),
- ``attention='flash'`` — Pallas blockwise kernel (``elephas_tpu.ops``),
- ``attention='ring'`` — sequence parallelism over the ``'seq'`` mesh
  axis via K/V rotation (``elephas_tpu.parallel.ring_attention``),
- ``attention='ulysses'`` — sequence parallelism via seq<->heads
  all-to-all re-sharding (``elephas_tpu.parallel.ulysses``),
- ``attention='auto'`` — topology-driven: under a bound ``'seq'`` mesh
  axis picks ulysses when the head count divides the axis (one dense
  shuffle instead of n−1 ring hops) and ring otherwise (works for ANY
  head count); outside shard_map falls back to the length-dispatched
  flash kernel. All choices are exact attention, so 'auto' is safe as
  a default — the user never has to know the topology math.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from elephas_tpu.models import register_model


def dense_causal_attention(q, k, v):
    """Reference softmax attention. q/k/v: (batch, heads, seq, head_dim)."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    seq = q.shape[2]
    mask = jnp.tril(jnp.ones((seq, seq), dtype=bool))
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    weights = nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


class SelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.float32
    attention: str = "dense"

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        qkv = nn.DenseGeneral((3, self.num_heads, head_dim), dtype=self.dtype,
                              name="qkv")(x)
        q, k, v = jnp.moveaxis(qkv, -3, 0)  # each (batch, seq, heads, head_dim)
        q = jnp.transpose(q, (0, 2, 1, 3))
        k = jnp.transpose(k, (0, 2, 1, 3))
        v = jnp.transpose(v, (0, 2, 1, 3))
        attention = self.attention
        if attention == "auto" and not self.is_initializing():
            # Resolved at trace time (axis size is static): sequence-
            # parallel layout by topology under a bound 'seq' axis, flash
            # dispatch otherwise. Exact attention either way.
            from elephas_tpu.parallel.ring_attention import seq_axis_size_or_none

            n = seq_axis_size_or_none()
            if n is None:
                attention = "flash"
            else:
                attention = "ulysses" if self.num_heads % n == 0 else "ring"
        if attention == "flash":
            from elephas_tpu.ops.attention import flash_attention

            out = flash_attention(q, k, v, causal=True)
        elif (
            attention in ("ring", "ulysses") and not self.is_initializing()
        ):
            # Sequence-parallel: must be called inside shard_map with the
            # sequence dimension sharded over the 'seq' mesh axis (see
            # elephas_tpu.parallel.seq_parallel). During module init (which
            # runs outside shard_map, where the axis is unbound) the dense
            # path traces instead — attention has no parameters, so the
            # param structure is identical. 'ring' rotates K/V shards;
            # 'ulysses' re-shards seq<->heads with two all_to_alls and
            # runs full-length flash attention per head subset.
            if attention == "ring":
                from elephas_tpu.parallel.ring_attention import ring_attention

                out = ring_attention(q, k, v, causal=True)
            else:
                from elephas_tpu.parallel.ulysses import ulysses_attention

                out = ulysses_attention(q, k, v, causal=True)
        elif attention in ("dense", "ring", "ulysses", "auto"):
            out = dense_causal_attention(q, k, v)
        else:
            # A silent dense fallback under sequence parallelism would
            # compute shard-LOCAL attention — wrong math that still
            # converges. Unknown names must fail loudly.
            raise ValueError(
                f"unknown attention={self.attention!r}; expected one of "
                "'dense', 'flash', 'ring', 'ulysses', 'auto'"
            )
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(x.shape[0], x.shape[1], d_model)
        return nn.DenseGeneral(d_model, dtype=self.dtype, name="out")(out)


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.float32
    attention: str = "dense"

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        x = x + SelfAttention(self.num_heads, dtype=self.dtype,
                              attention=self.attention)(y)
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        h = nn.Dense(d_model * self.mlp_ratio, dtype=self.dtype)(y)
        h = nn.gelu(h)
        return x + nn.Dense(d_model, dtype=self.dtype)(h)


class TransformerLM(nn.Module):
    vocab_size: int = 32000
    d_model: int = 256
    num_heads: int = 8
    num_layers: int = 4
    max_seq_len: int = 2048
    dtype: Any = jnp.float32
    attention: str = "dense"

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        seq = tokens.shape[1]
        x = nn.Embed(self.vocab_size, self.d_model, name="tok_embed")(
            tokens.astype(jnp.int32)
        )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_seq_len, self.d_model),
        )
        import jax

        from elephas_tpu.parallel.ring_attention import (
            require_seq_axis,
            seq_axis_size_or_none,
        )

        seq_parallel = self.attention in ("ring", "ulysses") or (
            # 'auto' is sequence-parallel exactly when a 'seq' axis is
            # bound (mirrors SelfAttention's trace-time resolution).
            self.attention == "auto" and seq_axis_size_or_none() is not None
        )
        if seq_parallel and not self.is_initializing():
            # Under sequence parallelism `tokens` is the local shard; index
            # the positional table at global positions.
            offset = require_seq_axis(
                feature=f"attention='{self.attention}'"
            ) * seq
            x = (x + jax.lax.dynamic_slice_in_dim(pos, offset, seq, axis=0)).astype(
                self.dtype
            )
        else:
            x = (x + pos[:seq]).astype(self.dtype)
        for _ in range(self.num_layers):
            x = Block(self.num_heads, dtype=self.dtype, attention=self.attention)(x)
        x = nn.LayerNorm(dtype=jnp.float32)(x.astype(jnp.float32))
        # Next-token logits, tied head kept separate for simplicity.
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="lm_head")(x)


@register_model("transformer_lm")
def build_transformer_lm(
    vocab_size=32000,
    d_model=256,
    num_heads=8,
    num_layers=4,
    max_seq_len=2048,
    dtype="float32",
    attention="dense",
):
    if attention not in ("dense", "flash", "ring", "ulysses", "auto"):
        raise ValueError(
            f"unknown attention={attention!r}; expected one of "
            "'dense', 'flash', 'ring', 'ulysses', 'auto'"
        )
    return TransformerLM(
        vocab_size=vocab_size,
        d_model=d_model,
        num_heads=num_heads,
        num_layers=num_layers,
        max_seq_len=max_seq_len,
        dtype=jnp.dtype(dtype),
        attention=attention,
    )
