"""Decoder-only transformer LM — the long-context flagship.

Not present in the reference (SURVEY.md §5.7: no long-context support
anywhere in elephas); included because long sequences are first-class in
the TPU rebuild. The attention implementation is pluggable:

- ``attention='dense'`` — plain softmax attention (XLA-fused),
- ``attention='flash'`` — Pallas blockwise kernel (``elephas_tpu.ops``),
- ``attention='ring'`` — sequence parallelism over the ``'seq'`` mesh
  axis via K/V rotation (``elephas_tpu.parallel.ring_attention``),
- ``attention='ulysses'`` — sequence parallelism via seq<->heads
  all-to-all re-sharding (``elephas_tpu.parallel.ulysses``),
- ``attention='auto'`` — topology-driven: under a bound ``'seq'`` mesh
  axis picks ulysses when the head count divides the axis (one dense
  shuffle instead of n−1 ring hops) and ring otherwise (works for ANY
  head count); outside shard_map falls back to the length-dispatched
  flash kernel. All choices are exact attention, so 'auto' is safe as
  a default — the user never has to know the topology math.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from elephas_tpu.models import register_model


def dense_causal_attention(q, k, v):
    """Reference softmax attention. q/k/v: (batch, heads, seq, head_dim)."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    seq = q.shape[2]
    mask = jnp.tril(jnp.ones((seq, seq), dtype=bool))
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    weights = nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


class SelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.float32
    attention: str = "dense"
    decode: bool = False

    @nn.compact
    def __call__(self, x, pad_offset=None, active=None):
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        qkv = nn.DenseGeneral((3, self.num_heads, head_dim), dtype=self.dtype,
                              name="qkv")(x)
        q, k, v = jnp.moveaxis(qkv, -3, 0)  # each (batch, seq, heads, head_dim)
        q = jnp.transpose(q, (0, 2, 1, 3))
        k = jnp.transpose(k, (0, 2, 1, 3))
        v = jnp.transpose(v, (0, 2, 1, 3))
        if self.decode:
            return self._decode_attend(x, q, k, v, d_model, pad_offset, active)
        attention = self.attention
        if attention == "auto" and not self.is_initializing():
            # Resolved at trace time (axis size is static): sequence-
            # parallel layout by topology under a bound 'seq' axis, flash
            # dispatch otherwise. Exact attention either way.
            from elephas_tpu.parallel.ring_attention import seq_axis_size_or_none

            n = seq_axis_size_or_none()
            if n is None:
                attention = "flash"
            else:
                attention = "ulysses" if self.num_heads % n == 0 else "ring"
        if attention == "flash":
            from elephas_tpu.ops.attention import flash_attention

            out = flash_attention(q, k, v, causal=True)
        elif (
            attention in ("ring", "ulysses") and not self.is_initializing()
        ):
            # Sequence-parallel: must be called inside shard_map with the
            # sequence dimension sharded over the 'seq' mesh axis (see
            # elephas_tpu.parallel.seq_parallel). During module init (which
            # runs outside shard_map, where the axis is unbound) the dense
            # path traces instead — attention has no parameters, so the
            # param structure is identical. 'ring' rotates K/V shards;
            # 'ulysses' re-shards seq<->heads with two all_to_alls and
            # runs full-length flash attention per head subset.
            if attention == "ring":
                from elephas_tpu.parallel.ring_attention import ring_attention

                out = ring_attention(q, k, v, causal=True)
            else:
                from elephas_tpu.parallel.ulysses import ulysses_attention

                out = ulysses_attention(q, k, v, causal=True)
        elif attention in ("dense", "ring", "ulysses", "auto"):
            out = dense_causal_attention(q, k, v)
        else:
            # A silent dense fallback under sequence parallelism would
            # compute shard-LOCAL attention — wrong math that still
            # converges. Unknown names must fail loudly.
            raise ValueError(
                f"unknown attention={self.attention!r}; expected one of "
                "'dense', 'flash', 'ring', 'ulysses', 'auto'"
            )
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(x.shape[0], x.shape[1], d_model)
        return nn.DenseGeneral(d_model, dtype=self.dtype, name="out")(out)

    def _decode_attend(self, x, q, k, v, d_model, pad_offset=None,
                       active=None):
        """Incremental (KV-cache) attention for autoregressive sampling.

        The cache is SHAPED on the init pass (which feeds a full-length
        dummy, flax's standard decode protocol) and FILLED by applies:
        the current block's k/v land at ``cache_index`` (seq may be >1 —
        batched PREFILL fills the whole prompt in one forward — or 1 per
        sampling step), and each query attends over everything cached up
        to its own position. ``cache_index`` is a scalar () when every
        row writes the same column (``generate`` — left-padding aligns
        the batch) or a (batch,) vector of independent per-row columns
        (the serving KV pool, where each slot is mid-decode at its own
        depth). ``pad_offset`` (batch,) masks each row's leading
        left-pad columns out of attention. ``active`` (batch,) bool —
        serving only — freezes INACTIVE rows' ``cache_index``: free pool
        slots ride along in the fixed-shape decode batch for the whole
        pool lifetime, and without the freeze their index vectors march
        past ``max_len`` while nothing is admitted. Training never
        touches this path — it exists for ``generate`` and
        ``serving``."""
        b, h, seq, head_dim = q.shape
        init_pass = not self.has_variable("cache", "cached_key")
        cached_key = self.variable(
            "cache", "cached_key",
            lambda: jnp.zeros((b, h, seq, head_dim), self.dtype),
        )
        cached_value = self.variable(
            "cache", "cached_value",
            lambda: jnp.zeros((b, h, seq, head_dim), self.dtype),
        )
        cache_index = self.variable(
            "cache", "cache_index", lambda: jnp.array(0, jnp.int32)
        )
        if init_pass:
            # Shaping pass only: ordinary causal attention; caches start
            # zeroed at the full length.
            out = dense_causal_attention(q, k, v)
        else:
            from elephas_tpu.ops.attention import cache_attention_mask

            idx = cache_index.value
            if idx.ndim == 0:
                ck = jax.lax.dynamic_update_slice(
                    cached_key.value, k.astype(self.dtype), (0, 0, idx, 0)
                )
                cv = jax.lax.dynamic_update_slice(
                    cached_value.value, v.astype(self.dtype), (0, 0, idx, 0)
                )
            else:
                # Per-row write positions: one scatter per row (vmapped
                # dynamic_update_slice over the batch dim).
                row_update = jax.vmap(
                    lambda cache, blk, i: jax.lax.dynamic_update_slice(
                        cache, blk, (0, i, 0)
                    )
                )
                ck = row_update(cached_key.value, k.astype(self.dtype), idx)
                cv = row_update(cached_value.value, v.astype(self.dtype), idx)
            cached_key.value = ck
            cached_value.value = cv
            if active is not None:
                if idx.ndim == 0:
                    raise ValueError(
                        "active masks require per-row (batch,) cache "
                        "indices (the serving pool layout); generate()'s "
                        "scalar index path never passes active"
                    )
                cache_index.value = jnp.where(active, idx + seq, idx)
            else:
                cache_index.value = idx + seq
            max_len = ck.shape[2]
            scale = 1.0 / np.sqrt(head_dim)
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, ck) * scale
            valid = cache_attention_mask(max_len, seq, idx, pad_offset)
            scores = jnp.where(valid, scores, jnp.finfo(scores.dtype).min)
            weights = nn.softmax(scores, axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd", weights, cv)
            if pad_offset is not None:
                # Queries at left-pad columns have NO valid key (their
                # softmax row is all -inf → NaN). Zero them so the pad
                # columns' residual stream stays finite — otherwise the
                # NEXT layer caches NaN keys there and 0-weight * NaN
                # poisons every real query downstream.
                qcols = idx + jnp.arange(seq) if jnp.asarray(idx).ndim == 0 \
                    else idx[:, None] + jnp.arange(seq)[None, :]
                qpad = qcols < pad_offset[:, None]  # (batch, seq)
                out = jnp.where(qpad[:, None, :, None], 0.0, out)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(
            x.shape[0], x.shape[1], d_model
        )
        return nn.DenseGeneral(d_model, dtype=self.dtype, name="out")(out)


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.float32
    attention: str = "dense"
    decode: bool = False

    @nn.compact
    def __call__(self, x, pad_offset=None, active=None):
        d_model = x.shape[-1]
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        x = x + SelfAttention(self.num_heads, dtype=self.dtype,
                              attention=self.attention,
                              decode=self.decode)(y, pad_offset=pad_offset,
                                                  active=active)
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        h = nn.Dense(d_model * self.mlp_ratio, dtype=self.dtype)(y)
        h = nn.gelu(h)
        return x + nn.Dense(d_model, dtype=self.dtype)(h)


class TransformerLM(nn.Module):
    vocab_size: int = 32000
    d_model: int = 256
    num_heads: int = 8
    num_layers: int = 4
    max_seq_len: int = 2048
    dtype: Any = jnp.float32
    attention: str = "dense"
    decode: bool = False

    @nn.compact
    def __call__(self, tokens, train: bool = False, pad_offset=None,
                 active=None):
        seq = tokens.shape[1]
        x = nn.Embed(self.vocab_size, self.d_model, name="tok_embed")(
            tokens.astype(jnp.int32)
        )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_seq_len, self.d_model),
        )
        if self.decode:
            return self._decode_forward(tokens, x, pos, seq, pad_offset,
                                        active)
        if pad_offset is not None or active is not None:
            raise ValueError(
                "pad_offset / active (ragged left-padded serving batches) "
                "are only supported on the decode=True path"
            )
        from elephas_tpu.parallel.ring_attention import (
            require_seq_axis,
            seq_axis_size_or_none,
        )

        seq_parallel = self.attention in ("ring", "ulysses") or (
            # 'auto' is sequence-parallel exactly when a 'seq' axis is
            # bound (mirrors SelfAttention's trace-time resolution).
            self.attention == "auto" and seq_axis_size_or_none() is not None
        )
        if seq_parallel and not self.is_initializing():
            # Under sequence parallelism `tokens` is the local shard; index
            # the positional table at global positions.
            offset = require_seq_axis(
                feature=f"attention='{self.attention}'"
            ) * seq
            x = (x + jax.lax.dynamic_slice_in_dim(pos, offset, seq, axis=0)).astype(
                self.dtype
            )
        else:
            x = (x + pos[:seq]).astype(self.dtype)
        for _ in range(self.num_layers):
            x = Block(self.num_heads, dtype=self.dtype, attention=self.attention)(x)
        x = nn.LayerNorm(dtype=jnp.float32)(x.astype(jnp.float32))
        # Next-token logits, tied head kept separate for simplicity.
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="lm_head")(x)

    def _decode_forward(self, tokens, x, pos, seq, pad_offset=None,
                        active=None):
        """Incremental forward for sampling: positional embedding from a
        module-level position counter (advanced by each apply's block
        length — the batched prompt prefill, then one token per sampling
        step), ordinary blocks with KV-cache attention. Init pass
        (full-length dummy) shapes the caches and the parameter tree
        identically to the training model, so trained params drop in.

        ``pos_index`` mirrors the layers' ``cache_index``: scalar for
        the aligned ``generate`` batch, (batch,) per-row for serving
        slots. With ``pad_offset`` set, a row's REAL position is its
        cache column minus its left-pad count, so a ragged row embeds
        its first real token at position 0 — token-identical to
        decoding that row alone."""
        init_pass = not self.has_variable("cache", "pos_index")
        pos_index = self.variable(
            "cache", "pos_index", lambda: jnp.array(0, jnp.int32)
        )
        if init_pass:
            x = (x + pos[:seq]).astype(self.dtype)
        else:
            idx = pos_index.value
            if active is not None:
                # Serving pool: free slots' position counters freeze in
                # lockstep with their frozen layer cache_index vectors.
                pos_index.value = jnp.where(active, idx + seq, idx)
            else:
                pos_index.value = idx + seq
            if idx.ndim == 0 and pad_offset is None:
                x = (
                    x + jax.lax.dynamic_slice_in_dim(pos, idx, seq, axis=0)
                ).astype(self.dtype)
            else:
                cols = idx[..., None] + jnp.arange(seq)  # (seq,) or (b, seq)
                if pad_offset is not None:
                    cols = cols - pad_offset[:, None]
                # Pad columns clip to position 0 — their embeddings are
                # garbage but masked out of every real query's attention.
                cols = jnp.clip(cols, 0, self.max_seq_len - 1)
                x = (x + jnp.take(pos, cols, axis=0)).astype(self.dtype)
        for _ in range(self.num_layers):
            x = Block(self.num_heads, dtype=self.dtype, attention="dense",
                      decode=True)(x, pad_offset=pad_offset, active=active)
        x = nn.LayerNorm(dtype=jnp.float32)(x.astype(jnp.float32))
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="lm_head")(x)


def sample_tokens(logits, key, greedy, top_k, temperature):
    """Shared sampling head for ``generate`` and the serving engine:
    greedy argmax, or top-k-truncated categorical at ``temperature``.
    ``greedy``/``top_k`` must be trace-time constants."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if top_k:
        # Keep the k highest logits, mask the rest to -inf: the
        # standard tail-truncation that stops temperature sampling
        # from wandering off the model's manifold. lax.top_k is
        # O(V) per step vs a full sort's O(V log V).
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(
            logits >= kth, logits, jnp.finfo(logits.dtype).min
        )
    return jax.random.categorical(key, logits / temperature).astype(
        jnp.int32
    )


def sample_tokens_at(logits, base_key, positions, greedy, top_k,
                     temperature):
    """Position-deterministic sampling: like ``sample_tokens`` but the
    PRNG key for each row is ``fold_in(base_key, positions[row])`` —
    the pad-free sequence position of the token being sampled. Any two
    programs that sample the same position of the same stream (plain
    decode, chunked prefill, speculative draft/verify) therefore draw
    the SAME random number, which is what makes speculative decode
    byte-identical to plain decode at temperature > 0, not just greedy.

    ``logits``: (N, vocab); ``positions``: (N,) int32. Greedy ignores
    the key entirely (argmax)."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(
            logits >= kth, logits, jnp.finfo(logits.dtype).min
        )
    sample_row = jax.vmap(
        lambda row, p: jax.random.categorical(
            jax.random.fold_in(base_key, p), row / temperature
        )
    )
    return sample_row(logits, positions).astype(jnp.int32)


# Trace-time counter: the traced body runs ONCE per compilation, so this
# counts compiles — tests assert ragged batches of varying lengths reuse
# one program (recompiles only on genuine shape/static changes).
_GENERATE_TRACES = 0


def generate_trace_count() -> int:
    """How many times the generate program has been (re)compiled."""
    return _GENERATE_TRACES


@functools.partial(
    jax.jit,
    static_argnames=("module", "max_new", "greedy", "top_k", "use_stop"),
)
def _generate_scan(module, params, prompt, cache, rng, max_new, greedy,
                   top_k, temperature, pad_offset, stop_token, use_stop):
    global _GENERATE_TRACES
    _GENERATE_TRACES += 1

    def sample(logits, key):
        return sample_tokens(logits, key, greedy, top_k, temperature)

    # PREFILL: one batched forward over the whole prompt fills every
    # layer's cache in parallel — O(plen) sequential single-token steps
    # would dominate long-context generation.
    logits, mutated = module.apply(
        {"params": params, "cache": cache}, prompt, mutable=["cache"],
        pad_offset=pad_offset,
    )
    rng, key = jax.random.split(rng)
    first = sample(logits[:, -1, :], key)
    done = (first == stop_token) if use_stop else jnp.zeros(
        first.shape, bool
    )

    def step(carry, _):
        tok, cache, rng, done = carry
        logits, mutated = module.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            mutable=["cache"],
            pad_offset=pad_offset,
        )
        rng, key = jax.random.split(rng)
        nxt = sample(logits[:, 0, :], key)
        if use_stop:
            # A finished row keeps emitting stop_token and stops
            # advancing — its output is frozen, per-row early stop
            # under one fixed-trip-count compiled program.
            nxt = jnp.where(done, stop_token, nxt)
            done = done | (nxt == stop_token)
        return (nxt, mutated["cache"], rng, done), nxt

    (_, _, _, _), rest = jax.lax.scan(
        step, (first, mutated["cache"], rng, done), None, length=max_new - 1
    )
    return jnp.concatenate([prompt, first[:, None], rest.T], axis=1)


def left_pad_prompts(prompts, pad_token: int = 0):
    """Left-pad a ragged batch of prompts to a (batch, max_len) array.

    ``prompts``: sequence of 1-D int token sequences (possibly of
    different lengths). Returns ``(padded, lengths)`` — real tokens of
    row ``i`` occupy the LAST ``lengths[i]`` columns, so every row's
    final prompt token lands in the same column and the whole batch
    decodes under one compiled program.
    """
    rows = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
    if any(len(r) < 1 for r in rows):
        raise ValueError("every prompt must have at least 1 token")
    lengths = np.array([len(r) for r in rows], np.int32)
    plen = int(lengths.max())
    padded = np.full((len(rows), plen), int(pad_token), np.int32)
    for i, r in enumerate(rows):
        padded[i, plen - len(r):] = r
    return padded, lengths


def make_decode_cache(decode_module, batch: int, total_len: int):
    """Zeroed KV caches for ``total_len`` columns straight from shapes
    (eval_shape: no param materialization, no full-length attention
    forward on dummies)."""
    cache_shapes = jax.eval_shape(
        lambda: decode_module.init(
            jax.random.PRNGKey(0), jnp.zeros((batch, total_len), jnp.int32)
        )
    )["cache"]
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes
    )


def make_paged_decode_cache(decode_module, max_slots: int, num_blocks: int,
                            block_size: int):
    """Zeroed PAGED decode cache: the same pytree structure as
    ``make_decode_cache`` but with every K/V leaf laid out as physical
    blocks ``(num_blocks, heads, block_size, head_dim)`` instead of one
    contiguous ``(max_slots, heads, max_len, head_dim)`` row per slot.
    A host-side block table maps ``slot -> block ids``; slots share
    blocks by holding the same id (reference-counted by the pool).

    Index leaves (``cache_index``/``pos_index``) stay per-SLOT
    ``(max_slots,)`` vectors — positions are a property of the logical
    sequence, not of physical block placement — so the same flax apply
    drives both layouts once the blocks are gathered contiguous."""
    cache_shapes = jax.eval_shape(
        lambda: decode_module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32)
        )
    )["cache"]

    def build(path, s):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("cached_key", "cached_value"):
            _, heads, _, head_dim = s.shape
            return jnp.zeros((num_blocks, heads, block_size, head_dim),
                             s.dtype)
        if name in ("cache_index", "pos_index"):
            return jnp.zeros((max_slots,), jnp.int32)
        return jnp.zeros(s.shape, s.dtype)

    return jax.tree_util.tree_map_with_path(build, cache_shapes)


def generate(
    compiled,
    prompt,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    seed: int = 0,
    params=None,
    prompt_lengths=None,
    stop_token: Optional[int] = None,
    pad_token: int = 0,
):
    """Autoregressive sampling from a ``TransformerLM`` — the inference
    half of the long-context story (absent in the reference, which has
    no generative models at all; SURVEY.md §5.7).

    ``prompt``: (batch, prompt_len) int tokens, or a RAGGED batch — a
    list/tuple of 1-D token sequences of different lengths, left-padded
    here with ``pad_token`` (equivalently, pass a pre-padded 2-D array
    plus ``prompt_lengths``). Ragged rows are masked through prefill
    and cache (padding never attended, positions counted from each
    row's first real token), so the output is token-identical to
    decoding each row alone — under ONE compiled program for the padded
    shape, no per-length recompiles (``generate_trace_count``).

    ``stop_token``: per-row early stop — a row that emits it freezes
    (keeps emitting ``stop_token``) while the rest of the batch decodes
    on. Returns (batch, prompt_len + max_new_tokens) tokens including
    the (padded) prompt. Greedy at ``temperature=0`` (default),
    categorical otherwise (temperature is a traced operand — sweeping
    it never recompiles); ``top_k > 0`` truncates sampling to the k
    most likely tokens.

    KV-cache incremental decoding: one batched PREFILL forward fills
    every layer's cache over the prompt, then one O(L·d) forward per
    sampled token, the whole loop one compiled program. Trained
    parameters drop in unchanged — the decode path shapes an identical
    parameter tree; models trained with ring/ulysses/flash attention
    sample through the cache path (same math, single device).
    """
    module = compiled.module
    if not isinstance(module, TransformerLM):
        raise TypeError(
            f"generate() samples TransformerLM models, got {type(module).__name__}"
        )
    params = params if params is not None else compiled.params
    if isinstance(prompt, (list, tuple)):
        if prompt_lengths is not None:
            raise ValueError(
                "pass prompt_lengths only with a pre-padded 2-D prompt array"
            )
        prompt, prompt_lengths = left_pad_prompts(prompt, pad_token)
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2 or prompt.shape[1] < 1:
        raise ValueError(
            f"prompt must be (batch, prompt_len>=1), got {prompt.shape}"
        )
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if not 0 <= top_k <= module.vocab_size:
        raise ValueError(
            f"top_k must be in [0, vocab_size={module.vocab_size}], got {top_k}"
        )
    b, plen = prompt.shape
    pad_offset = None
    if prompt_lengths is not None:
        lengths = np.asarray(prompt_lengths, np.int32).reshape(-1)
        if lengths.shape != (b,):
            raise ValueError(
                f"prompt_lengths must have shape ({b},), got {lengths.shape}"
            )
        if (lengths < 1).any() or (lengths > plen).any():
            raise ValueError(
                f"prompt_lengths must be in [1, {plen}], got {lengths}"
            )
        # All-full-length batches keep the (faster) unmasked program.
        if (lengths < plen).any():
            pad_offset = jnp.asarray(plen - lengths)
    if stop_token is not None and not 0 <= stop_token < module.vocab_size:
        raise ValueError(
            f"stop_token must be in [0, vocab_size={module.vocab_size}), "
            f"got {stop_token}"
        )
    total = plen + max_new_tokens
    if total > module.max_seq_len:
        raise ValueError(
            f"prompt_len {plen} + max_new_tokens {max_new_tokens} exceeds "
            f"max_seq_len {module.max_seq_len}"
        )
    # decode=True with attention='dense': the cache path replaces the
    # attention impl; sequence-parallel training configs sample fine.
    decode_module = dataclasses.replace(module, decode=True, attention="dense")
    cache = make_decode_cache(decode_module, b, total)
    out = _generate_scan(
        decode_module, params, prompt, cache,
        jax.random.PRNGKey(seed), max_new_tokens,
        float(temperature) <= 0.0, int(top_k), jnp.float32(temperature),
        pad_offset,
        jnp.int32(0 if stop_token is None else stop_token),
        stop_token is not None,
    )
    return np.asarray(out)


@register_model("transformer_lm")
def build_transformer_lm(
    vocab_size=32000,
    d_model=256,
    num_heads=8,
    num_layers=4,
    max_seq_len=2048,
    dtype="float32",
    attention="dense",
):
    if attention not in ("dense", "flash", "ring", "ulysses", "auto"):
        raise ValueError(
            f"unknown attention={attention!r}; expected one of "
            "'dense', 'flash', 'ring', 'ulysses', 'auto'"
        )
    return TransformerLM(
        vocab_size=vocab_size,
        d_model=d_model,
        num_heads=num_heads,
        num_layers=num_layers,
        max_seq_len=max_seq_len,
        dtype=jnp.dtype(dtype),
        attention=attention,
    )
