"""Small convnet (BASELINE config 2: MNIST CNN via async mode)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn

from elephas_tpu.models import register_model


class SimpleCNN(nn.Module):
    """Conv-pool stack + dense head; NHWC inputs; logits out."""

    channels: Sequence[int] = (32, 64)
    dense_width: int = 128
    num_classes: int = 10
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        for ch in self.channels:
            x = nn.Conv(ch, kernel_size=(3, 3), padding="SAME")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.dense_width)(x)
        x = nn.relu(x)
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


@register_model("cnn")
def build_cnn(channels=(32, 64), dense_width=128, num_classes=10, dropout_rate=0.0):
    return SimpleCNN(
        channels=tuple(channels),
        dense_width=dense_width,
        num_classes=num_classes,
        dropout_rate=dropout_rate,
    )
