"""Model zoo + registry.

The reference ships no models — users hand it compiled Keras models, and
its examples build MNIST MLP/CNN, IMDB LSTM, CIFAR ResNet (BASELINE.md
configs). The rebuild provides those architectures as flax modules so the
five benchmark configs are runnable out of the box, plus a *registry* so
architectures serialize by name (the TPU-native analogue of Keras's
``model_to_json`` arch string — SURVEY.md §2.1 serialization row).
"""

from __future__ import annotations

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}


def register_model(name: str):
    """Register a module builder under ``name`` for arch serialization."""

    def wrap(builder: Callable) -> Callable:
        _REGISTRY[name] = builder
        return wrap.__wrapped__ if hasattr(wrap, "__wrapped__") else builder

    return wrap


def get_model(name: str, **kwargs):
    """Build a registered module; tags it so its arch serializes by name."""
    if name not in _REGISTRY:
        raise ValueError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    module = _REGISTRY[name](**kwargs)
    config = {"name": name, "kwargs": kwargs}
    try:
        object.__setattr__(module, "_elephas_config", config)
    except AttributeError:  # exotic Module subclass with __slots__
        pass
    return module


def registered_models():
    return sorted(_REGISTRY)


# Import for side effect: populate the registry.
from elephas_tpu.models import mlp, cnn, resnet, lstm, transformer  # noqa: E402,F401
from elephas_tpu.models.mlp import MLP  # noqa: E402,F401
from elephas_tpu.models.cnn import SimpleCNN  # noqa: E402,F401
from elephas_tpu.models.resnet import ResNet18  # noqa: E402,F401
from elephas_tpu.models.lstm import LSTMClassifier  # noqa: E402,F401
from elephas_tpu.models.transformer import (  # noqa: E402,F401
    TransformerLM,
    generate,
)
