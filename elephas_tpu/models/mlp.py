"""MLP classifier (BASELINE config 1: MNIST MLP, SURVEY.md §6)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from elephas_tpu.models import register_model


class MLP(nn.Module):
    """Dense stack with ReLU + dropout, logits out (no softmax — losses
    expect logits)."""

    features: Sequence[int] = (128, 128)
    num_classes: int = 10
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        for width in self.features:
            x = nn.Dense(width)(x)
            x = nn.relu(x)
            if self.dropout_rate > 0:
                x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


@register_model("mlp")
def build_mlp(features=(128, 128), num_classes=10, dropout_rate=0.0):
    return MLP(features=tuple(features), num_classes=num_classes, dropout_rate=dropout_rate)


class MaskedMLP(nn.Module):
    """Width-bucketed MLP: layers are built at ``features`` (bucket)
    width but only the first ``active[i]`` units of layer *i* are live.

    The point is EXECUTABLE SHARING across hyperparameter trials
    (VERDICT r4 #6): XLA compiles per shape, so a width search over
    {64, 128, 256} pays a full ~12s recompile per fresh width. Here the
    jitted program is shaped on the bucket only — the active-width mask
    lives in the ``batch_stats`` collection, entering the program as a
    runtime ARRAY argument, so every width in a bucket runs the same
    executable and only bucket boundaries ever compile.

    Exactness: padded units' activations are multiplied by a 0/1 mask,
    so they contribute nothing forward and receive zero gradient —
    parameters and optimizer moments behave as an ``active``-width
    network (the padded columns just ride along at their init values).
    Initialization is corrected for the masking: a Dense layer fed by a
    masked layer sees ``bucket`` input dims but only ``active`` of them
    are live, so its kernel init std is rescaled by
    ``sqrt(bucket/active)`` to match a true active-width network's
    fan-in variance — without this, activations shrink as the bucket
    grows and the loss trajectory would jump discontinuously across
    bucket boundaries (width 128 vs 129). The compute cost is the
    bucket's, the statistics are the active width's — the standard
    padding trade.
    """

    features: Sequence[int] = (128,)
    active: Sequence[int] = (128,)
    num_classes: int = 10
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        if len(self.active) != len(self.features):
            raise ValueError(
                f"active widths {self.active} must match bucket layout "
                f"{self.features} layer-for-layer"
            )

        def fan_in_corrected(bucket_in: int, live_in: int):
            # lecun_normal with the LIVE fan-in: the kernel physically
            # has bucket_in rows, but only live_in carry signal.
            base = nn.initializers.lecun_normal()
            scale = (bucket_in / live_in) ** 0.5

            def init(key, shape, dtype=jnp.float32):
                return base(key, shape, dtype) * scale

            return init

        def pick_init(prev_bucket, prev_live):
            # First layer (no masked predecessor): true input fan-in.
            if prev_bucket is None:
                return nn.linear.default_kernel_init
            return fan_in_corrected(prev_bucket, prev_live)

        x = x.reshape((x.shape[0], -1))
        prev_bucket = prev_live = None
        for i, (bucket, live) in enumerate(zip(self.features, self.active)):
            if not 0 < live <= bucket:
                raise ValueError(
                    f"layer {i}: active width {live} outside (0, {bucket}]"
                )
            mask = self.variable(
                "batch_stats",
                f"mask_{i}",
                lambda: (jnp.arange(bucket) < live).astype(jnp.float32),
            )
            x = nn.Dense(bucket, kernel_init=pick_init(prev_bucket, prev_live))(x)
            x = nn.relu(x) * mask.value
            if self.dropout_rate > 0:
                x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
            prev_bucket, prev_live = bucket, live
        return nn.Dense(
            self.num_classes, kernel_init=pick_init(prev_bucket, prev_live)
        )(x)


@register_model("mlp_masked")
def build_masked_mlp(features=(128,), active=None, num_classes=10, dropout_rate=0.0):
    return MaskedMLP(
        features=tuple(features),
        active=tuple(active) if active is not None else tuple(features),
        num_classes=num_classes,
        dropout_rate=dropout_rate,
    )
