"""MLP classifier (BASELINE config 1: MNIST MLP, SURVEY.md §6)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn

from elephas_tpu.models import register_model


class MLP(nn.Module):
    """Dense stack with ReLU + dropout, logits out (no softmax — losses
    expect logits)."""

    features: Sequence[int] = (128, 128)
    num_classes: int = 10
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        for width in self.features:
            x = nn.Dense(width)(x)
            x = nn.relu(x)
            if self.dropout_rate > 0:
                x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


@register_model("mlp")
def build_mlp(features=(128, 128), num_classes=10, dropout_rate=0.0):
    return MLP(features=tuple(features), num_classes=num_classes, dropout_rate=dropout_rate)
