"""LSTM text classifier (BASELINE config 3: IMDB LSTM via the estimator).

TPU-first: the recurrence is a single ``nn.RNN``/``lax.scan`` over the
sequence (static shapes, compiler-friendly control flow) — no Python loop,
no dynamic lengths inside jit. Inputs are int32 token ids, right-padded.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from elephas_tpu.models import register_model


class LSTMClassifier(nn.Module):
    vocab_size: int = 20000
    embed_dim: int = 128
    hidden_dim: int = 128
    num_classes: int = 2
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        x = nn.Embed(self.vocab_size, self.embed_dim)(tokens.astype(jnp.int32))
        rnn = nn.RNN(nn.OptimizedLSTMCell(self.hidden_dim))
        x = rnn(x)  # (batch, seq, hidden)
        x = x[:, -1, :]  # final state
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


@register_model("lstm")
def build_lstm(vocab_size=20000, embed_dim=128, hidden_dim=128, num_classes=2, dropout_rate=0.0):
    return LSTMClassifier(
        vocab_size=vocab_size,
        embed_dim=embed_dim,
        hidden_dim=hidden_dim,
        num_classes=num_classes,
        dropout_rate=dropout_rate,
    )
