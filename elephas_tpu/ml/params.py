"""Spark-ML-style ``Has*`` parameter mixins.

Reference: ``elephas/ml/params.py`` (SURVEY.md §2.1, §5.6) — ~14 tiny
mixin classes, one per hyperparameter, each exposing a getter/setter so
pipeline stages are introspectable and serializable. pyspark is absent,
so this module provides a dependency-free ``Param`` descriptor with the
same chainable ``set_x()/get_x()`` surface (setters return ``self``,
Spark-style) plus ``explain_params()`` / ``param_map()`` for
introspection and stage save/load.
"""

from __future__ import annotations

from typing import Any, Dict


class Param:
    """A named, documented, defaulted stage parameter (descriptor)."""

    def __init__(self, name: str, doc: str, default: Any = None):
        self.name = name
        self.doc = doc
        self.default = default

    def __set_name__(self, owner, attr_name):
        self._attr = "_param_" + self.name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        if not hasattr(obj, self._attr) and isinstance(self.default, (dict, list)):
            # Never hand out the shared class-level default for mutable
            # values — a stage mutating it in place would leak into every
            # other stage.
            import copy

            setattr(obj, self._attr, copy.deepcopy(self.default))
        return getattr(obj, self._attr, self.default)

    def __set__(self, obj, value):
        setattr(obj, self._attr, value)


class HasParams:
    """Base: parameter discovery, explain, and dict round-trip."""

    @classmethod
    def _params(cls) -> Dict[str, Param]:
        out: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for name, value in vars(klass).items():
                if isinstance(value, Param):
                    out[value.name] = value
        return out

    def param_map(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in self._params()}

    def set_params(self, **kwargs) -> "HasParams":
        params = self._params()
        for key, value in kwargs.items():
            if key not in params:
                raise ValueError(f"unknown param {key!r}; known: {sorted(params)}")
            setattr(self, key, value)
        return self

    def explain_params(self) -> str:
        lines = []
        for name, param in sorted(self._params().items()):
            lines.append(f"{name}: {param.doc} (default: {param.default!r}, "
                         f"current: {getattr(self, name)!r})")
        return "\n".join(lines)


def _mixin(param_name: str, doc: str, default=None, class_name: str = None):
    """Build one reference-style ``Has*`` mixin with get/set methods."""
    param = Param(param_name, doc, default)

    def setter(self, value):
        setattr(self, param_name, value)
        return self

    def getter(self):
        return getattr(self, param_name)

    cls = type(
        class_name or f"Has{param_name.title().replace('_', '')}",
        (HasParams,),
        {
            param_name: param,
            f"set_{param_name}": setter,
            f"get_{param_name}": getter,
        },
    )
    return cls


# The reference's mixin set (SURVEY.md §2.1 "ML Param mixins" row), with
# snake_case param names matching SparkModel's constructor kwargs.
HasKerasModelConfig = _mixin(
    "keras_model_config",
    "serialized model architecture (registry config or model_to_dict payload)",
    class_name="HasKerasModelConfig",
)
HasMode = _mixin("mode", "training mode: synchronous|asynchronous|hogwild", "asynchronous")
HasFrequency = _mixin("frequency", "coordination granularity: batch|epoch|fit", "epoch")
HasNumberOfClasses = _mixin("nb_classes", "number of label classes", 10,
                            class_name="HasNumberOfClasses")
HasNumberOfWorkers = _mixin("num_workers", "number of data-parallel workers (chips)", None,
                            class_name="HasNumberOfWorkers")
HasEpochs = _mixin("epochs", "training epochs", 10)
HasBatchSize = _mixin("batch_size", "per-worker batch size", 32)
HasVerbosity = _mixin("verbose", "verbosity level", 0, class_name="HasVerbosity")
HasValidationSplit = _mixin("validation_split", "fraction held out for validation", 0.0)
HasCategoricalLabels = _mixin("categorical", "labels are class indices to one-hot", True,
                              class_name="HasCategoricalLabels")
HasLoss = _mixin("loss", "loss name (engine.losses) or callable", "categorical_crossentropy")
HasMetrics = _mixin("metrics", "metric names", ("acc",))
HasOptimizerConfig = _mixin("optimizer_config", "optimizer name/config dict",
                            {"name": "sgd"}, class_name="HasOptimizerConfig")
HasOutputCol = _mixin("output_col", "prediction column name", "prediction",
                      class_name="HasOutputCol")
HasFeaturesCol = _mixin("features_col", "features column name", "features")
HasLabelCol = _mixin("label_col", "label column name", "label")
HasParameterServerMode = _mixin(
    "parameter_server_mode", "async weight transport: local|http|socket", "local"
)
HasAutotune = _mixin(
    "autotune",
    "one-shot per-workload compile-option A/B at fit start "
    "(utils/compiler.py; choice lands in history as compile_autotune)",
    False,
)
