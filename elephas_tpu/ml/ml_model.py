"""Estimator/Transformer pipeline stages over columnar DataFrames.

Reference: ``elephas/ml_model.py::{ElephasEstimator, ElephasTransformer,
load_ml_estimator, load_ml_transformer}`` (SURVEY.md §2.1, §3.3):
DataFrame in / DataFrame out, making distributed training a
``Pipeline`` stage with save/load. The pyspark.ml machinery is replaced
by the dependency-free ``Has*`` mixins in ``elephas_tpu.ml.params`` and
the columnar ``DataFrame`` in ``elephas_tpu.data.dataframe``; training
itself delegates to ``SparkModel`` exactly like the reference (§3.3
call stack: estimator -> df_to_simple_rdd -> SparkModel.fit ->
transformer with trained weights).
"""

from __future__ import annotations

import pickle
from typing import Optional

import numpy as np

from elephas_tpu.api.spark_model import SparkModel
from elephas_tpu.data.dataframe import DataFrame, df_to_simple_rdd
from elephas_tpu.ml.params import (
    HasAutotune,
    HasBatchSize,
    HasCategoricalLabels,
    HasEpochs,
    HasFeaturesCol,
    HasFrequency,
    HasKerasModelConfig,
    HasLabelCol,
    HasLoss,
    HasMetrics,
    HasMode,
    HasNumberOfClasses,
    HasNumberOfWorkers,
    HasOptimizerConfig,
    HasOutputCol,
    HasParameterServerMode,
    HasValidationSplit,
    HasVerbosity,
)
from elephas_tpu.serialize.serialization import dict_to_model, model_to_dict


class ElephasEstimator(
    HasKerasModelConfig,
    HasAutotune,
    HasMode,
    HasFrequency,
    HasNumberOfClasses,
    HasNumberOfWorkers,
    HasEpochs,
    HasBatchSize,
    HasVerbosity,
    HasValidationSplit,
    HasCategoricalLabels,
    HasLoss,
    HasMetrics,
    HasOptimizerConfig,
    HasOutputCol,
    HasFeaturesCol,
    HasLabelCol,
    HasParameterServerMode,
):
    """Trainable pipeline stage: ``fit(df) -> ElephasTransformer``.

    ``keras_model_config`` accepts either a ``model_to_dict`` payload or a
    registry config ``{"name": ..., "kwargs": ...}`` (the TPU-native
    analogue of the reference's Keras arch JSON string).
    """

    def __init__(self, **kwargs):
        # Runtime-only fit callbacks ((epoch, state, metrics) callables) —
        # deliberately NOT a persisted Param: callables don't serialize
        # into a pipeline stage. Used by the parity harness for epoch
        # timestamps and by users for checkpointing during estimator fits.
        self.callbacks = tuple(kwargs.pop("callbacks", ()))
        self.set_params(**kwargs)

    def _build_model(self):
        config = self.keras_model_config
        if config is None:
            raise ValueError("set_keras_model_config(...) before fit")
        if "arch" in config:  # full model_to_dict payload
            compiled = dict_to_model(config)
            # Stage params override the payload's training attributes.
            from elephas_tpu.api.compile import CompiledModel

            compiled = CompiledModel(
                compiled.module,
                params=compiled.params,
                optimizer=self.optimizer_config,
                loss=self.loss,
                metrics=list(self.metrics),
                batch_stats=compiled.batch_stats,
                model_config=compiled.model_config,
            )
            return compiled
        # registry config
        from elephas_tpu.api.compile import CompiledModel
        from elephas_tpu.models import get_model

        module = get_model(config["name"], **config.get("kwargs", {}))
        input_shape = config.get("input_shape")
        if input_shape is None:
            raise ValueError(
                "registry keras_model_config needs 'input_shape' to initialize"
            )
        return CompiledModel(
            module,
            optimizer=self.optimizer_config,
            loss=self.loss,
            metrics=list(self.metrics),
            input_shape=tuple(input_shape),
            input_dtype=np.dtype(config.get("input_dtype", "float32")),
        )

    def _fit(self, df: DataFrame) -> "ElephasTransformer":
        compiled = self._build_model()
        rdd = df_to_simple_rdd(
            df,
            categorical=self.categorical,
            nb_classes=self.nb_classes,
            features_col=self.features_col,
            label_col=self.label_col,
            num_partitions=self.num_workers or 1,
        )
        spark_model = SparkModel(
            compiled,
            mode=self.mode,
            frequency=self.frequency,
            parameter_server_mode=self.parameter_server_mode,
            num_workers=self.num_workers,
            batch_size=self.batch_size,
            autotune=self.autotune,
        )
        spark_model.fit(
            rdd,
            epochs=self.epochs,
            batch_size=self.batch_size,
            verbose=self.verbose,
            validation_split=self.validation_split,
            callbacks=getattr(self, "callbacks", ()),
        )
        return ElephasTransformer(
            model_payload=model_to_dict(spark_model.master_network),
            output_col=self.output_col,
            features_col=self.features_col,
            categorical=self.categorical,
            history=spark_model.training_histories[-1],
        )

    # pyspark.ml parity: public fit() delegates to _fit().
    def fit(self, df: DataFrame) -> "ElephasTransformer":
        return self._fit(df)

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump({"kind": "estimator", "params": self.param_map()}, f)


class ElephasTransformer(HasOutputCol, HasFeaturesCol, HasCategoricalLabels):
    """Fitted stage: ``transform(df)`` appends a prediction column.

    Reference §3.3: broadcast weights, mapPartitions predict, re-attach
    column — here a sharded jit forward over the mesh via SparkModel.
    """

    def __init__(
        self,
        model_payload: dict,
        output_col: str = "prediction",
        features_col: str = "features",
        categorical: bool = True,
        history: Optional[dict] = None,
    ):
        self.model_payload = model_payload
        self.set_output_col(output_col)
        self.set_features_col(features_col)
        self.set_categorical(categorical)
        self.history = history or {}
        self._spark_model: Optional[SparkModel] = None

    def get_model(self):
        """The trained CompiledModel (reference ``Transformer.get_model``)."""
        return self._model().master_network

    def _model(self) -> SparkModel:
        if self._spark_model is None:
            self._spark_model = SparkModel(
                dict_to_model(self.model_payload), mode="synchronous"
            )
        return self._spark_model

    def _transform(self, df: DataFrame) -> DataFrame:
        features = df[self.features_col]
        outputs = self._model().predict(features)
        if self.categorical:
            predictions = np.argmax(outputs, axis=-1).astype(np.float32)
        elif outputs.ndim > 1 and outputs.shape[-1] == 1:
            predictions = np.squeeze(outputs, axis=-1)  # keep the row dim
        else:
            predictions = outputs
        return df.with_column(self.output_col, predictions)

    def transform(self, df: DataFrame) -> DataFrame:
        return self._transform(df)

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump(
                {
                    "kind": "transformer",
                    "model_payload": self.model_payload,
                    "output_col": self.output_col,
                    "features_col": self.features_col,
                    "categorical": self.categorical,
                    "history": self.history,
                },
                f,
            )


def load_ml_estimator(path: str) -> ElephasEstimator:
    with open(path, "rb") as f:
        payload = pickle.load(f)
    if payload.get("kind") != "estimator":
        raise ValueError(f"{path} does not contain an ElephasEstimator")
    return ElephasEstimator(**payload["params"])


def load_ml_transformer(path: str) -> ElephasTransformer:
    with open(path, "rb") as f:
        payload = pickle.load(f)
    if payload.get("kind") != "transformer":
        raise ValueError(f"{path} does not contain an ElephasTransformer")
    return ElephasTransformer(
        model_payload=payload["model_payload"],
        output_col=payload["output_col"],
        features_col=payload["features_col"],
        categorical=payload["categorical"],
        history=payload["history"],
    )
