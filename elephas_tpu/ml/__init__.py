"""ML-pipeline façade (reference L5: ``elephas/ml_model.py`` + ``elephas/ml/``)."""

from elephas_tpu.ml.ml_model import (  # noqa: F401
    ElephasEstimator,
    ElephasTransformer,
    load_ml_estimator,
    load_ml_transformer,
)
