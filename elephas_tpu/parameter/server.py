"""Parameter servers: in-process (local), HTTP, and raw-socket transports.

Reference: ``elephas/parameter/server.py::{HttpServer, SocketServer}``
(SURVEY.md §2.1): a Flask app with ``GET /parameters`` / ``POST /update``
or a threaded TCP server speaking ``'g'``/``'u'`` framed pickle messages,
locking iff mode is ``asynchronous``.

All three servers here share one ``ParameterBuffer`` (HBM-resident store +
lock discipline); the HTTP/socket ones add a wire transport for cross-host
workers. Flask is replaced by the stdlib ``ThreadingHTTPServer`` — same
protocol, no dependency.
"""

from __future__ import annotations

import os
import pickle
import socketserver
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import jax

from elephas_tpu.parameter.base import BaseParameterServer
from elephas_tpu.parameter.buffer import ParameterBuffer
from elephas_tpu.utils import sockets as socket_utils


def _default_bind_host() -> str:
    """Loopback by default: the wire servers run unauthenticated pickle, so
    exposure beyond the host must be an explicit opt-in — ``host='0.0.0.0'``
    (passed by the async engine when a run is actually multi-host) or
    ``ELEPHAS_PS_BIND`` in the environment."""
    return os.environ.get("ELEPHAS_PS_BIND", "127.0.0.1")


def _dial_host(bind_host: str) -> str:
    """Address a same-host client should dial for a server bound to
    ``bind_host``. A wildcard bind listens on loopback too, so dial
    127.0.0.1; a concrete bind (e.g. ``ELEPHAS_PS_BIND=10.0.0.5``) does
    NOT listen on loopback, so the client must dial that address."""
    if bind_host in ("", "0.0.0.0", "::", "*"):
        return "127.0.0.1"
    return bind_host


class LocalServer(BaseParameterServer):
    """In-process server: workers share the HBM buffer directly.

    The TPU-native default for single-host training — "serving" is just
    handing out a buffer handle; pulls are device-to-device copies.
    """

    def __init__(self, params, lock: bool = True, device: Optional[jax.Device] = None,
                 granularity: str = "tree"):
        self.buffer = ParameterBuffer(params, lock=lock, device=device,
                                      granularity=granularity)

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass

    def get_parameters(self):
        return self.buffer.get()

    def client(self):
        from elephas_tpu.parameter.client import LocalClient

        return LocalClient(self.buffer)


class _BarrierBook:
    """Named arrival counters — the PS doubles as the cross-host control
    plane (a host "arrives" at a tag; peers poll the count). Chosen over
    device collectives for teardown barriers because hosts can drift by
    minutes during async training, far past collective-rendezvous
    deadlines."""

    def __init__(self):
        self._counts: dict = {}
        self._lock = threading.Lock()

    def arrive(self, tag: str) -> int:
        with self._lock:
            self._counts[tag] = self._counts.get(tag, 0) + 1
            return self._counts[tag]

    def count(self, tag: str) -> int:
        with self._lock:
            return self._counts.get(tag, 0)


class HttpServer(BaseParameterServer):
    """HTTP transport over a ParameterBuffer (reference ``HttpServer``).

    Protocol parity: ``GET /parameters`` returns pickled weights,
    ``POST /update`` applies a pickled delta. Runs in a daemon thread.
    Control-plane extension: ``POST /barrier/<tag>`` (arrive) and
    ``GET /barrier/<tag>`` (count) back cross-host barriers.
    """

    def __init__(
        self,
        params,
        lock: bool = True,
        port: int = 4000,
        device: Optional[jax.Device] = None,
        host: Optional[str] = None,
        granularity: str = "tree",
    ):
        self.buffer = ParameterBuffer(params, lock=lock, device=device,
                                      granularity=granularity)
        self.host = host if host is not None else _default_bind_host()
        self.port = port
        self.barriers = _BarrierBook()
        self._httpd = None
        self._thread = None

    def start(self) -> None:
        buffer = self.buffer
        barriers = self.barriers

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silence per-request stderr spam
                pass

            def _send_count(self, count: int) -> None:
                body = str(count).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.rstrip("/")
                if path == "/health":
                    body = b"ok"
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/parameters":
                    payload = pickle.dumps(
                        buffer.get_numpy(), protocol=pickle.HIGHEST_PROTOCOL
                    )
                    self.send_response(200)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                elif path.startswith("/barrier/"):
                    self._send_count(barriers.count(path[len("/barrier/"):]))
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                path = self.path.rstrip("/")
                if path == "/update":
                    length = int(self.headers.get("Content-Length", 0))
                    delta = pickle.loads(self.rfile.read(length))
                    buffer.apply_delta(delta)
                    self.send_response(200)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                elif path.startswith("/barrier/"):
                    self._send_count(barriers.arrive(path[len("/barrier/"):]))
                else:
                    self.send_error(404)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        if self.port == 0:  # ephemeral port (tests)
            self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def get_parameters(self):
        return self.buffer.get()

    @property
    def master_url(self) -> str:
        return socket_utils.determine_master(self.port)

    def client(self):
        from elephas_tpu.parameter.client import HttpClient

        return HttpClient(f"{_dial_host(self.host)}:{self.port}")


class _SocketHandler(socketserver.BaseRequestHandler):
    def handle(self):
        buffer = self.server.buffer  # type: ignore[attr-defined]
        barriers = self.server.barriers  # type: ignore[attr-defined]
        try:
            while True:
                kind, payload = socket_utils.receive(self.request)
                if kind == "g":
                    socket_utils.send(self.request, buffer.get_numpy())
                elif kind == "u":
                    buffer.apply_delta(payload)
                    socket_utils.send(self.request, b"ok")
                elif kind == "b":  # barrier arrive(tag) -> count
                    socket_utils.send(self.request, barriers.arrive(payload))
                elif kind == "c":  # barrier count(tag)
                    socket_utils.send(self.request, barriers.count(payload))
                else:
                    break
        except (ConnectionError, OSError):
            pass


class _ThreadingTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class SocketServer(BaseParameterServer):
    """Raw-TCP transport (reference ``SocketServer``): persistent
    connections carrying ``('g', None)`` / ``('u', delta)`` frames."""

    def __init__(
        self,
        params,
        lock: bool = True,
        port: int = 4000,
        device: Optional[jax.Device] = None,
        host: Optional[str] = None,
        granularity: str = "tree",
    ):
        self.buffer = ParameterBuffer(params, lock=lock, device=device,
                                      granularity=granularity)
        self.host = host if host is not None else _default_bind_host()
        self.port = port
        self.barriers = _BarrierBook()
        self._server = None
        self._thread = None

    def start(self) -> None:
        self._server = _ThreadingTCPServer((self.host, self.port), _SocketHandler)
        self._server.buffer = self.buffer  # type: ignore[attr-defined]
        self._server.barriers = self.barriers  # type: ignore[attr-defined]
        if self.port == 0:
            self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def get_parameters(self):
        return self.buffer.get()

    def client(self):
        from elephas_tpu.parameter.client import SocketClient

        return SocketClient(f"{_dial_host(self.host)}:{self.port}")


def make_server(
    mode: str,
    params,
    lock: bool = True,
    port: int = 4000,
    device: Optional[jax.Device] = None,
    host: Optional[str] = None,
    granularity: str = "tree",
) -> BaseParameterServer:
    """Factory keyed on the reference's ``parameter_server_mode``.
    ``granularity`` ('tree'|'leaf') sets the hogwild apply isolation —
    see ``ParameterBuffer``'s memory-model note."""
    if mode == "local":
        return LocalServer(params, lock=lock, device=device, granularity=granularity)
    if mode == "http":
        return HttpServer(params, lock=lock, port=port, device=device, host=host,
                          granularity=granularity)
    if mode == "socket":
        return SocketServer(params, lock=lock, port=port, device=device, host=host,
                            granularity=granularity)
    raise ValueError(f"parameter_server_mode must be local|http|socket, got {mode!r}")
