"""Parameter servers: in-process (local), HTTP, and raw-socket transports.

Reference: ``elephas/parameter/server.py::{HttpServer, SocketServer}``
(SURVEY.md §2.1): a Flask app with ``GET /parameters`` / ``POST /update``
or a threaded TCP server speaking ``'g'``/``'u'`` framed pickle messages,
locking iff mode is ``asynchronous``.

All three servers here share one ``ParameterBuffer`` (HBM-resident store +
lock discipline); the HTTP/socket ones add a wire transport for cross-host
workers. Flask is replaced by the stdlib ``ThreadingHTTPServer`` — same
protocol, no dependency.

Wire-transport data path (this PR's throughput rebuild):

- Pulls serve from a **version-gated snapshot cache** (``_SnapshotCache``):
  the tree is snapshotted under the buffer's read lock, fetched to host and
  encoded AFTER the lock is released, and the encoded frame is reused for
  every pull until ``ParameterBuffer.version`` moves — N workers pulling an
  unchanged model cost ONE serialization, not N (the reference pickled the
  whole weight list per request, under the handler).
- Clients that advertise their last-seen version get a 12-byte
  **not-modified** frame when the buffer hasn't moved — O(header) on the
  wire instead of O(model).
- Bodies are **packed-codec** frames (``parameter.wire``) for new peers and
  pickle for legacy ones, negotiated by magic bytes (HTTP) / explicit frame
  kinds (socket); pushes accept either codec on one path.
"""

from __future__ import annotations

import hmac
import json
import os
import socket
import socketserver
import tempfile
import threading
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import jax

from elephas_tpu import obs
from elephas_tpu.parameter import wire
from elephas_tpu.parameter.base import BaseParameterServer
from elephas_tpu.parameter.buffer import ParameterBuffer
from elephas_tpu.utils import sockets as socket_utils
from elephas_tpu.utils import locksan


def _ps_counters(transport: str):
    """(cache_hit, bytes_tx, bytes_rx) server-side data-path counters.

    The byte counters are one labeled family per direction — the
    transport is a LABEL (``ps_bytes_tx_total{transport="socket"}``), so
    Prometheus can sum across transports or split by one, instead of the
    dimension being baked into per-transport metric names."""
    reg = obs.default_registry()
    return (
        reg.counter("ps_cache_hit_total",
                    "pulls answered with a not-modified frame"),
        reg.counter("ps_bytes_tx_total",
                    "payload bytes sent by the PS servers",
                    labelnames=("transport",)).labels(transport=transport),
        reg.counter("ps_bytes_rx_total",
                    "payload bytes received by the PS servers",
                    labelnames=("transport",)).labels(transport=transport),
    )


def _lag_of(buffer, seen_version) -> Optional[int]:
    """One push's version lag: the buffer's live version minus the
    version the worker trained against (clamped at 0 — a racing hogwild
    apply can only make the live version newer). None for frames
    without a ``seen_version`` stamp (legacy peers)."""
    if seen_version is None:
        return None
    try:
        return max(0, int(buffer.version) - int(seen_version))
    except (TypeError, ValueError):
        return None


def _note_staleness(ledger, worker, lag, seen_version, nbytes,
                    sync_interval=None) -> None:
    """Feed one APPLIED push into the health surfaces (ledger row +
    labeled histogram). Called after the admission decision, for
    accepted/damped pushes only — a rejected push was never applied, so
    it must not count as an update or skew the lag distribution (the
    ledger's ``rejected`` column, bumped by ``_admit``, is its record).
    ``lag=None`` (unstamped legacy frame) counts as unstamped coverage.
    ``sync_interval`` is the pusher's self-reported adaptive
    units-per-push (None when it doesn't stamp one) — kept on the
    worker's ledger row for the fleet SYNC column."""
    from elephas_tpu.obs.health import record_staleness

    record_staleness(ledger, worker, lag, nbytes=nbytes,
                     version=seen_version,
                     registry=obs.default_registry(),
                     sync_interval=sync_interval)


def _parse_trace_header(raw: Optional[str]):
    """``X-Elephas-Trace: <trace_id>-<span_id>`` → TraceContext | None.
    Malformed values are dropped, never fatal — tracing must not be able
    to take down the data path."""
    if not raw:
        return None
    trace_id, sep, span_id = raw.partition("-")
    if not sep or not trace_id or not span_id:
        return None
    return obs.TraceContext(trace_id, span_id)


def _as_trace_ctx(tc):
    """A wire-carried ``(trace_id, span_id)`` pair → TraceContext | None
    (tolerates lists from JSON headers and junk from old peers)."""
    if (isinstance(tc, (tuple, list)) and len(tc) == 2
            and all(isinstance(x, str) for x in tc)):
        return obs.TraceContext(tc[0], tc[1])
    return None


def _new_boot_id() -> str:
    """Per-server-instance boot id (random, minted at construction).

    Version-gated pulls are keyed on ``(boot, version)``: a warm-restarted
    server resumes the WAL's durable version COUNTER, so the counter alone
    can collide with a pre-restart value a client already cached — e.g. the
    server replays to v=41 while a client still holds pre-crash v=41
    content that never made it into the WAL. A fresh boot id makes every
    restart a cache miss, so the first pull after recovery always carries
    the full body."""
    return os.urandom(6).hex()


def _heartbeat_timeout(explicit: Optional[float] = None) -> float:
    """Suspect threshold for the failure detector, seconds.

    Precedence: explicit argument > ``ELEPHAS_HEARTBEAT_TIMEOUT`` env >
    5.0 default. A malformed env value warns and falls back rather than
    crashing server construction."""
    if explicit is not None:
        return float(explicit)
    raw = os.environ.get("ELEPHAS_HEARTBEAT_TIMEOUT", "5")
    try:
        return float(raw)
    except ValueError:
        warnings.warn(
            f"ELEPHAS_HEARTBEAT_TIMEOUT={raw!r} is not a number; "
            "using the 5.0s default",
            RuntimeWarning,
            stacklevel=2,
        )
        return 5.0


def _staleness_bound(explicit, env_var: str) -> Optional[int]:
    """Optional staleness bound, versions. Precedence mirrors
    ``_heartbeat_timeout``: explicit argument > env var > None
    (unbounded). A malformed env value warns and falls back rather than
    crashing server construction."""
    if explicit is not None:
        return int(explicit)
    raw = os.environ.get(env_var)
    if raw is None or raw == "":
        return None
    try:
        return int(raw)
    except ValueError:
        warnings.warn(
            f"{env_var}={raw!r} is not an integer; staleness bound "
            "disabled",
            RuntimeWarning,
            stacklevel=2,
        )
        return None


class AdmissionPolicy:
    """Accept / damp / reject one stamped delta by its version lag.

    The enforcement half of the staleness plane: ``StalenessLedger``
    measures every push's version lag; this policy acts on it at the
    apply site. Three regimes, per delta:

    - ``lag <= soft`` (or no bounds set): **accept** at full weight.
    - ``soft < lag <= max_staleness``: **damp** — the delta is applied
      scaled by ``1 / (1 + lag - soft)``, the DeepSpark-style staleness
      decay (arXiv 1602.08191): a slightly-stale gradient still carries
      signal, a very stale one mostly noise.
    - ``lag > max_staleness``: **reject** — the delta is not applied at
      all and the pusher gets a typed ``wire.encode_rejected`` frame
      telling it to re-pull and sync more often.

    Unstamped pushes (legacy peers that declare no ``seen_version``)
    have ``lag=None`` and are ALWAYS accepted at full weight — bounds
    only bind peers that opted into the staleness contract, so old
    pickle workers keep their exact pre-policy behavior.

    Bounds resolve like every other server knob: explicit constructor
    argument > ``ELEPHAS_MAX_STALENESS`` / ``ELEPHAS_STALENESS_SOFT``
    env vars > None (unbounded / no damping).
    """

    def __init__(self, max_staleness: Optional[int] = None,
                 soft: Optional[int] = None):
        self.max_staleness = _staleness_bound(
            max_staleness, "ELEPHAS_MAX_STALENESS")
        self.soft = _staleness_bound(soft, "ELEPHAS_STALENESS_SOFT")

    def decide(self, lag: Optional[int]):
        """``(verdict, weight)``: ``("accept", 1.0)``, ``("damp", w<1)``,
        or ``("reject", 0.0)``."""
        if lag is None:
            return "accept", 1.0
        if self.max_staleness is not None and lag > self.max_staleness:
            return "reject", 0.0
        if self.soft is not None and lag > self.soft:
            return "damp", 1.0 / (1.0 + (lag - self.soft))
        return "accept", 1.0

    def __repr__(self):
        return (f"AdmissionPolicy(max_staleness={self.max_staleness}, "
                f"soft={self.soft})")


def _scale_tree(tree, weight: float):
    """Scale a delta's float leaves by the damping weight. Decoded wire
    leaves are read-only ``frombuffer`` views, so the multiply's copy is
    the first (and only) host copy a damped apply pays. Non-float leaves
    (step counters and the like) pass through unscaled — a fractional
    counter increment is meaningless."""
    import numpy as np

    def scale(leaf):
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f":
            return arr * arr.dtype.type(weight)
        return leaf

    return jax.tree_util.tree_map(scale, tree)


def _admit(policy, ledger, worker, lag, tree, transport: str, hsp=None):
    """Run one stamped push through the admission policy.

    Returns ``(verdict, tree)`` — the tree scaled for a damp, ``None``
    for a reject (the caller answers with the typed frame and skips
    apply + WAL). Feeds the counters, the per-worker ledger columns,
    and — on a reject — the flight recorder, so every non-accept
    decision is visible on all three obs surfaces."""
    verdict, weight = policy.decide(lag)
    if verdict == "accept":
        return verdict, tree
    reg = obs.default_registry()
    if verdict == "damp":
        reg.counter("ps_delta_damped_total",
                    "stamped deltas applied at reduced weight by the "
                    "staleness admission policy").inc()
        ledger.record_damped(worker)
        if hsp:
            hsp.note(admission="damp", weight=round(weight, 4))
        return verdict, _scale_tree(tree, weight)
    reg.counter("ps_delta_rejected_total",
                "stamped deltas refused by the staleness admission policy",
                labelnames=("reason",)).labels(reason="max_staleness").inc()
    ledger.record_rejected(worker)
    obs.default_flight_recorder().note(
        "delta_rejected", "warn", worker=worker, lag=lag,
        max_staleness=policy.max_staleness, transport=transport,
    )
    if hsp:
        hsp.note(admission="reject", lag=lag)
    return verdict, None


def _make_detector(heartbeat_timeout: Optional[float]):
    """Deferred import: ``resilience`` pulls in ``parameter.client`` at
    package-import time, so a module-level import here would make the
    layering order-sensitive (whichever package imports first wins)."""
    from elephas_tpu.resilience.liveness import FailureDetector

    return FailureDetector(suspect_after=_heartbeat_timeout(heartbeat_timeout))


def _attach_wal(buffer: ParameterBuffer, wal_dir: str, wal_every: int,
                wal_keep: int = 3):
    """Warm-restart ``buffer`` from the newest durable WAL snapshot and
    return the ``WalWriter`` that keeps the log moving.

    Cold start (empty/corrupt WAL directory) is the ``NoCheckpointError``
    branch: the buffer keeps the params it was constructed with and the
    version line starts fresh."""
    from elephas_tpu.checkpoint.checkpoint import NoCheckpointError
    from elephas_tpu.resilience.wal import SnapshotWAL, WalWriter

    wal = SnapshotWAL(wal_dir, keep=wal_keep)
    try:
        version, tree = wal.restore_latest()
    except NoCheckpointError:
        pass  # cold start: serve the constructor params
    else:
        buffer.set(tree, version=version)
        # A restore means a previous server life ended uncleanly (or at
        # least left a WAL behind) — worth a line in the anomaly log.
        obs.default_flight_recorder().note(
            "wal_restore", "info", version=version, wal_dir=wal_dir,
        )
    return WalWriter(buffer, wal, every=wal_every)


class _SnapshotCache:
    """Serialize once per ``ParameterBuffer.version``, outside the lock.

    ``frames(codec)`` returns ``(version, payload)`` where ``payload`` is
    a reusable ``wire.Frames`` (packed) or ``bytes`` (legacy pickle).
    The snapshot is taken under the buffer's READ lock only
    (``get_numpy_with_version``); the host fetch and the encode run after
    release, so writers are never blocked on serialization. A private
    lock single-flights the encode — concurrent pulls at the same
    version wait for one encoding instead of each doing their own.

    Staleness safety: the buffer reads its version BEFORE the snapshot,
    so a racing hogwild apply can only make the cached content NEWER
    than its key — the next pull re-encodes (version mismatch) rather
    than ever serving a stale not-modified (see
    ``ParameterBuffer.get_with_version``).
    """

    def __init__(self, buffer: ParameterBuffer, boot: Optional[str] = None):
        self._buffer = buffer
        self._boot = boot  # stamped into packed headers (see _new_boot_id)
        self._encode_lock = locksan.make_lock("_SnapshotCache._encode_lock")
        self._entries: dict = {}  # codec -> (version, frames|bytes)

    def frames(self, codec: str):
        entry = self._entries.get(codec)
        if entry is not None and entry[0] == self._buffer.version:
            return entry
        with self._encode_lock:
            entry = self._entries.get(codec)
            if entry is not None and entry[0] == self._buffer.version:
                return entry
            version, snap = self._buffer.get_numpy_with_version()
            if codec == "packed":
                payload = wire.encode_tree(snap, version=version, boot=self._boot)  # lock-ok: single-flight encode; the lock exists to dedupe this work
            else:
                payload = wire.encode_pickle(snap)  # lock-ok: single-flight encode
            entry = (version, payload)
            self._entries[codec] = entry
            return entry


def _pinned_payload(cache: _SnapshotCache, wal_writer, version: int):
    """Payload for a version-PINNED pull (rollout plane): the live
    snapshot when the pin IS the buffer's current version, else the
    durable WAL frame at exactly that version, else ``None`` — the
    typed "can no longer serve it" answer the client surfaces as
    ``VersionUnavailable``. Pinned reads deliberately skip the
    not-modified negotiation: the caller wants THESE bytes regardless
    of its cached position (rollback must not race live pushes)."""
    live, frames = cache.frames("packed")
    if live == version:
        return frames
    if wal_writer is not None:
        raw = wal_writer.wal.read_version(version)
        if raw is not None:
            return socket_utils.RawPayload([raw])
    return None


def _dump_flight_on_kill(boot: str, wal_dir: Optional[str]) -> Optional[str]:
    """Crash-path flight-recorder dump: next to the WAL when there is
    one (the operator is already looking there after a crash), else the
    tempdir. Best-effort — a full disk must not mask the kill itself."""
    recorder = obs.default_flight_recorder()
    if not recorder.enabled:
        return None
    base = wal_dir if wal_dir else tempfile.gettempdir()
    path = os.path.join(base, f"flight-{boot}.json")
    try:
        return recorder.dump(path)
    except OSError:
        return None


def _default_bind_host() -> str:
    """Loopback by default: the wire servers run unauthenticated pickle, so
    exposure beyond the host must be an explicit opt-in — ``host='0.0.0.0'``
    (passed by the async engine when a run is actually multi-host) or
    ``ELEPHAS_PS_BIND`` in the environment."""
    return os.environ.get("ELEPHAS_PS_BIND", "127.0.0.1")


def _dial_host(bind_host: str) -> str:
    """Address a same-host client should dial for a server bound to
    ``bind_host``. A wildcard bind listens on loopback too, so dial
    127.0.0.1; a concrete bind (e.g. ``ELEPHAS_PS_BIND=10.0.0.5``) does
    NOT listen on loopback, so the client must dial that address."""
    if bind_host in ("", "0.0.0.0", "::", "*"):
        return "127.0.0.1"
    return bind_host


class LocalServer(BaseParameterServer):
    """In-process server: workers share the HBM buffer directly.

    The TPU-native default for single-host training — "serving" is just
    handing out a buffer handle; pulls are device-to-device copies.
    """

    def __init__(self, params, lock: bool = True, device: Optional[jax.Device] = None,
                 granularity: str = "tree",
                 heartbeat_timeout: Optional[float] = None):
        self.buffer = ParameterBuffer(params, lock=lock, device=device,
                                      granularity=granularity)
        # Liveness bookkeeping works in-process too: the elastic pool's
        # monitor thread polls membership through a client regardless of
        # transport, and local-mode worker threads can still die.
        self.detector = _make_detector(heartbeat_timeout)

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass

    def get_parameters(self):
        return self.buffer.get()

    def client(self):
        from elephas_tpu.parameter.client import LocalClient

        return LocalClient(self.buffer, detector=self.detector)


class _BarrierBook:
    """Named arrival counters — the PS doubles as the cross-host control
    plane (a host "arrives" at a tag; peers poll the count). Chosen over
    device collectives for teardown barriers because hosts can drift by
    minutes during async training, far past collective-rendezvous
    deadlines."""

    def __init__(self):
        self._counts: dict = {}
        self._lock = threading.Lock()

    def arrive(self, tag: str) -> int:
        with self._lock:
            self._counts[tag] = self._counts.get(tag, 0) + 1
            return self._counts[tag]

    def count(self, tag: str) -> int:
        with self._lock:
            return self._counts.get(tag, 0)


class _ObservableServerMixin:
    """Shared observability plumbing for the wire servers: per-request
    tracer resolution, opsd mounting, and the crash-path flight dump.

    Expects the host class to set ``tracer`` (override or None),
    ``ops_port``, ``ops``, ``flight_dump``, ``_wal_dir``, ``buffer``,
    ``detector``, ``boot``, ``host``, ``port``, ``ledger``, ``alerts``,
    ``store``.
    """

    def _tracer(self):
        # Resolved per use: an enable_tracing() after start() is seen.
        return self.tracer if self.tracer is not None else obs.default_tracer()

    def _attach_telemetry_store(self, store_dir) -> None:
        """Mount the durable telemetry journal (``obs.store``) next to
        the WAL: the process-global flight recorder and this server's
        alert engine tee into it from construction on, so anomalies
        that precede ``start()`` (WAL restore, tail healing) are
        journaled too. ``"auto"`` resolves to ``<wal_dir>/telemetry``
        (disabled when there is no WAL); an explicit path mounts there
        regardless — standbys need that, they share the shard's
        ``wal_dir`` but must not share a store directory (open-time
        tail healing assumes one live writer per directory)."""
        self.store = None
        if store_dir == "auto":
            store_dir = (os.path.join(self._wal_dir, "telemetry")
                         if self._wal_dir else None)
        if store_dir is None:
            return
        self.store = obs.TelemetryStore(
            store_dir, role=self.role, boot=self.boot,
            flight=obs.default_flight_recorder())
        obs.default_flight_recorder().attach_store(self.store)
        self.alerts.attach_store(self.store)
        tracer = self._tracer()
        if getattr(tracer, "enabled", False):
            tracer.attach_store(self.store)

    def _close_store(self, reason: str) -> None:
        store = getattr(self, "store", None)
        if store is None:
            return
        obs.default_flight_recorder().detach_store(store)
        self.alerts.detach_store(store)
        sampler = getattr(self, "_ops_history", None)
        if sampler is not None:
            sampler.detach_store(store)
        tracer = self._tracer()
        if hasattr(tracer, "detach_store"):
            tracer.detach_store(store)
        store.close(reason=reason)
        self.store = None

    def _mount_ops(self, transport: str) -> None:
        if self.ops_port is None:
            return
        from elephas_tpu.obs.devprof import (DeviceProfiler,
                                             record_device_memory)
        from elephas_tpu.obs.history import HistorySampler
        from elephas_tpu.obs.opsd import OpsServer

        buffer, detector, boot = self.buffer, self.detector, self.boot
        ledger, alerts = self.ledger, self.alerts
        # History rings + device profiler ride on the mount: sampling
        # runs on a daemon thread (scrape-independent), profiler dumps
        # land next to the WAL — one directory per incarnation holds
        # the flight dump, the WAL, and any device captures.
        self._ops_history = HistorySampler(
            extra_fn=record_device_memory).start()
        if getattr(self, "store", None) is not None:
            self._ops_history.attach_store(self.store)
        self._ops_profiler = DeviceProfiler(out_dir=self._wal_dir)
        self.ops = OpsServer(
            port=self.ops_port,
            tracer=self.tracer,  # None → live process default
            role=self.role, boot=boot,
            vars_fn=lambda: {"boot": boot, "version": buffer.version,
                             "transport": transport,
                             "ps_host": self.host, "ps_port": self.port},
            health_fn=lambda: {"membership": detector.membership()},
            workers_fn=ledger.snapshot,
            alerts_fn=alerts.scrape,
            history=self._ops_history,
            profiler=self._ops_profiler,
            # Group members get this stamped by ShardGroup (the group
            # topology doc); standalone servers serve the empty shell.
            shards_fn=getattr(self, "shards_fn", None),
            incidents_fn=(self.store.doc
                          if getattr(self, "store", None) is not None
                          else None),
        ).start()

    def _unmount_ops(self) -> None:
        if self.ops is not None:
            self.ops.stop()
            self.ops = None
        sampler = getattr(self, "_ops_history", None)
        if sampler is not None:
            sampler.stop()
            self._ops_history = None

    def _record_kill(self) -> None:
        """Flight-record the crash and dump the ring to disk — AFTER
        connections are severed (the crash is atomic to clients; the
        version in the note is the one the kill froze) but before
        ``kill()`` returns, so the artifact always exists even though
        the 'process' skips every clean-shutdown sync. The telemetry
        store closes AFTER the note, so ``ps_kill`` is the last
        journaled event — the record a post-mortem rebuild names as
        the trigger (a real crash handler closes the journal from the
        same hook that dumps the flight ring)."""
        obs.default_flight_recorder().note(
            "ps_kill", "error", boot=self.boot, version=self.buffer.version,
        )
        self.flight_dump = _dump_flight_on_kill(self.boot, self._wal_dir)
        self._close_store("kill")


class HttpServer(_ObservableServerMixin, BaseParameterServer):
    """HTTP transport over a ParameterBuffer (reference ``HttpServer``).

    Protocol parity: ``GET /parameters`` returns pickled weights,
    ``POST /update`` applies a pickled delta. Runs in a daemon thread.
    Control-plane extension: ``POST /barrier/<tag>`` (arrive) and
    ``GET /barrier/<tag>`` (count) back cross-host barriers.
    """

    def __init__(
        self,
        params,
        lock: bool = True,
        port: int = 4000,
        device: Optional[jax.Device] = None,
        host: Optional[str] = None,
        granularity: str = "tree",
        auth_key: Optional[bytes] = None,
        wal_dir: Optional[str] = None,
        wal_every: int = 1,
        wal_keep: int = 3,
        heartbeat_timeout: Optional[float] = None,
        tracer=None,
        ops_port: Optional[int] = None,
        role: str = "ps",
        shard_info: Optional[dict] = None,
        max_staleness: Optional[int] = None,
        staleness_soft: Optional[int] = None,
        store_dir: Optional[str] = "auto",
    ):
        """``auth_key``: shared HMAC-SHA256 secret. When set, every
        request must carry ``X-Elephas-Auth`` = hexmac(method + path +
        nonce + ts + body) plus fresh ``X-Elephas-Nonce``/``X-Elephas-TS``
        headers (verified BEFORE the body is unpickled — a bad tag is a
        403, a replayed/stale nonce likewise, and nothing is applied);
        every response body is signed bound to the REQUEST's nonce so a
        captured response can't be replayed to a later request either.
        ``/health`` stays open (liveness probe, no pickles). Multi-host
        fits enable this by default with a DCN-broadcast secret (async
        engine).

        ``wal_dir``: write-ahead snapshot directory (``resilience.wal``).
        Construction warm-restarts the buffer from the newest durable
        snapshot (cold start when empty) and every accepted push is made
        durable BEFORE it is acked, at most ``wal_every`` versions behind.
        ``heartbeat_timeout``: failure-detector suspect threshold
        (default ``ELEPHAS_HEARTBEAT_TIMEOUT`` or 5s; dead at 2x).

        ``tracer``: span recorder for server-side handle spans (default:
        the process-global tracer, resolved per request so a later
        ``enable_tracing()`` is picked up). Handle spans adopt the
        client's wire-propagated trace context and are tagged with this
        server's boot id — across a kill/warm-restart the trace id
        stays the client's while the boot id changes.
        ``ops_port``: mount an ``obs.opsd.OpsServer`` (loopback by
        default) on this port at ``start()`` — 0 picks a free port
        (read ``.ops.port``).
        ``role``: the ops/fleet role stamp (``ps`` standalone;
        ``ps/shard<i>`` / ``ps/standby`` inside a group). ``shard_info``:
        the group handshake doc (``{digest, shard, k}``) served from
        ``GET /shardinfo`` with the live boot id merged in — unset means
        the route 404s and sharded clients refuse this server.
        ``max_staleness``/``staleness_soft``: the bounded-staleness
        admission knobs (see ``AdmissionPolicy``; env fallbacks
        ``ELEPHAS_MAX_STALENESS``/``ELEPHAS_STALENESS_SOFT``).
        ``store_dir``: durable telemetry journal directory
        (``obs.store``). The default ``"auto"`` mounts at
        ``<wal_dir>/telemetry`` when a WAL is configured (none
        otherwise); ``None`` disables; an explicit path mounts there —
        shard-group standbys pass one, they share the shard's
        ``wal_dir`` but need their own journal directory."""
        self.buffer = ParameterBuffer(params, lock=lock, device=device,
                                      granularity=granularity)
        self.host = host if host is not None else _default_bind_host()
        self.port = port
        self.auth_key = auth_key
        self.replay_guard = socket_utils.ReplayGuard() if auth_key else None
        self.barriers = _BarrierBook()
        self.boot = _new_boot_id()
        self.detector = _make_detector(heartbeat_timeout)
        self.wal_writer = (
            _attach_wal(self.buffer, wal_dir, wal_every, wal_keep=wal_keep)
            if wal_dir else None
        )
        self.tracer = tracer
        self.ops_port = ops_port
        self.ops = None
        # Training-health surfaces: the per-worker staleness/contribution
        # ledger the push handlers feed (opsd /workers) and the SLO alert
        # engine evaluated on every /alerts scrape.
        self.ledger = obs.StalenessLedger()
        self.alerts = obs.AlertEngine()
        self.admission = AdmissionPolicy(max_staleness, staleness_soft)
        self.role = role
        self.shard_info = shard_info
        self.flight_dump: Optional[str] = None
        self._wal_dir = wal_dir
        self._attach_telemetry_store(store_dir)
        self._httpd = None
        self._thread = None

    def start(self) -> None:
        buffer = self.buffer
        barriers = self.barriers
        auth_key = self.auth_key
        replay_guard = self.replay_guard
        boot = self.boot
        detector = self.detector
        wal_writer = self.wal_writer
        cache = self._cache = _SnapshotCache(buffer, boot=boot)
        cache_hits, bytes_tx, bytes_rx = _ps_counters("http")
        tracer_of = self._tracer
        ledger = self.ledger
        admission = self.admission
        shard_info = self.shard_info

        class Handler(BaseHTTPRequestHandler):
            # Small replies (not-modified frames, barrier acks) must not
            # stall behind Nagle + delayed-ACK coalescing.
            disable_nagle_algorithm = True

            def log_message(self, *args):  # silence per-request stderr spam
                pass

            def _authed(self, body: bytes = b"") -> bool:
                if auth_key is None:
                    return True
                claim = self.headers.get("X-Elephas-Auth", "")
                nonce_hex = self.headers.get("X-Elephas-Nonce", "")
                ts_str = self.headers.get("X-Elephas-TS", "")
                try:
                    nonce = bytes.fromhex(nonce_hex)
                    ts = float(ts_str)
                except ValueError:
                    nonce, ts = b"", 0.0
                want = socket_utils.frame_mac(
                    auth_key,
                    self.command.encode() + self.path.encode()
                    + nonce + ts_str.encode() + body,
                ).hex()
                if nonce and hmac.compare_digest(claim, want):
                    try:
                        replay_guard.check(nonce, ts)
                        self._req_nonce = nonce
                        return True
                    except ConnectionError:
                        pass
                self.send_error(403, "authentication failed")
                return False

            def _reply(self, body, content_type: Optional[str] = None,
                       version: Optional[int] = None) -> None:
                # body: bytes OR wire.Frames — frames are written chunk
                # by chunk (no header+payload concatenation).
                chunks = body.chunks if isinstance(body, socket_utils.RawPayload) \
                    else [body]
                nbytes = body.nbytes if isinstance(body, socket_utils.RawPayload) \
                    else len(body)
                self.send_response(200)
                if content_type:
                    self.send_header("Content-Type", content_type)
                if version is not None:
                    self.send_header("X-Elephas-Version", str(version))
                if auth_key is not None:
                    # Bound to the request nonce: stale responses can't
                    # be replayed into a different exchange. Incremental
                    # MAC over the chunks — no full-body copy.
                    self.send_header(
                        "X-Elephas-Auth",
                        socket_utils.chunks_mac(
                            auth_key,
                            [getattr(self, "_req_nonce", b""), *chunks],
                        ).hex(),
                    )
                self.send_header("Content-Length", str(nbytes))
                self.end_headers()
                for chunk in chunks:
                    self.wfile.write(chunk)

            def do_GET(self):  # noqa: N802
                path = self.path.rstrip("/")
                if path == "/health":
                    self._reply(b"ok")  # open: liveness probe, no pickles
                    return
                if not self._authed():
                    return
                if path == "/parameters":
                    # Adopt the client's wire-propagated trace context:
                    # this handle span becomes the remote child of the
                    # client's ps/pull span in the merged trace, tagged
                    # with THIS boot id (a warm restart keeps the trace
                    # id, changes the boot).
                    ctx = _parse_trace_header(
                        self.headers.get("X-Elephas-Trace"))
                    with obs.activate(ctx), tracer_of().span(
                            "ps/handle_pull", boot=boot, transport="http"):
                        # Codec negotiation: packed-aware clients say so;
                        # the default stays pickle for legacy peers. The
                        # encoded snapshot comes from the version-gated
                        # cache — the buffer lock is never held across
                        # serialization.
                        pinned = self.headers.get("X-Elephas-Pinned")
                        if pinned is not None:
                            try:
                                pin = int(pinned)
                            except ValueError:
                                self.send_error(400, "bad pinned version")
                                return
                            payload = _pinned_payload(cache, wal_writer, pin)
                            if payload is None:
                                self.send_error(
                                    404, "pinned version unavailable")
                                return
                            bytes_tx.inc(payload.nbytes)
                            self._reply(
                                payload,
                                content_type="application/octet-stream",
                                version=pin)
                            return
                        codec = "packed" if self.headers.get(
                            "X-Elephas-Codec") == "packed" else "pickle"
                        known = self.headers.get("X-Elephas-Version")
                        known_boot = self.headers.get("X-Elephas-Boot")
                        version, payload = cache.frames(codec)
                        # Not-modified requires the BOOT to match too:
                        # after a warm restart the version counter resumes
                        # an old line, so a bare version match could alias
                        # content from a previous server life
                        # (see _new_boot_id).
                        if codec == "packed" and known is not None \
                                and known == str(version) \
                                and known_boot == boot:
                            payload = wire.encode_not_modified(version)
                            cache_hits.inc()
                        bytes_tx.inc(payload.nbytes if isinstance(
                            payload, socket_utils.RawPayload) else len(payload))
                        self._reply(payload,
                                    content_type="application/octet-stream",
                                    version=version)
                elif path == "/membership":
                    self._reply(json.dumps(detector.membership()).encode(),
                                content_type="application/json")
                elif path == "/shardinfo":
                    # Group handshake: the plan identity plus the LIVE
                    # boot id (fencing compares boots, not addresses).
                    if shard_info is None:
                        self.send_error(404)
                        return
                    self._reply(
                        json.dumps(dict(shard_info, boot=boot)).encode(),
                        content_type="application/json")
                elif path.startswith("/barrier/"):
                    self._reply(str(barriers.count(path[len("/barrier/"):])).encode())
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                path = self.path.rstrip("/")
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if not self._authed(body):
                    return
                if path == "/update":
                    # _authed() ran on the raw body FIRST — neither codec
                    # sees unauthenticated bytes when a key is set. The
                    # body self-describes (packed magic vs pickle), so
                    # one endpoint serves both codecs' pushes.
                    bytes_rx.inc(len(body))
                    # Trace context: the HTTP header, or (packed bodies)
                    # the frame's own "tc" header. Decoding is zero-copy,
                    # so doing it before the handle span costs ~nothing.
                    tree, body_tc, seen, worker, syncint = \
                        wire.decode_push(body)
                    # Pickle bodies carry their staleness stamps as
                    # request headers instead of in-frame.
                    if seen is None:
                        raw_seen = self.headers.get("X-Elephas-Seen-Version")
                        if raw_seen is not None:
                            try:
                                seen = int(raw_seen)
                            except ValueError:
                                seen = None
                    if worker is None:
                        worker = self.headers.get("X-Elephas-Worker")
                    if syncint is None:
                        raw_si = self.headers.get("X-Elephas-Sync-Interval")
                        if raw_si is not None:
                            try:
                                syncint = float(raw_si)
                            except ValueError:
                                syncint = None
                    ctx = (_parse_trace_header(
                               self.headers.get("X-Elephas-Trace"))
                           or _as_trace_ctx(body_tc))
                    tracer = tracer_of()
                    with obs.activate(ctx), tracer.span(
                            "ps/handle_push", boot=boot,
                            transport="http") as hsp:
                        lag = _lag_of(buffer, seen)
                        if hsp and lag is not None:
                            hsp.note(staleness=lag, worker=worker)
                        verdict, tree = _admit(admission, ledger, worker,
                                               lag, tree, "http", hsp)
                        if verdict != "reject":
                            # Ledger update only for applied pushes —
                            # a reject is recorded by _admit as
                            # ``rejected`` and must not count as an
                            # update or enter the lag histogram.
                            _note_staleness(ledger, worker, lag, seen,
                                            len(body),
                                            sync_interval=syncint)
                        if verdict == "reject":
                            # Typed refusal instead of an apply: the
                            # stamped peer decodes this into a
                            # StaleDeltaRejected. Unstamped legacy
                            # pushes can never reach here (lag None
                            # always accepts).
                            frame = wire.encode_rejected(
                                buffer.version, lag,
                                admission.max_staleness)
                            bytes_tx.inc(frame.nbytes)
                            self._reply(
                                frame,
                                content_type="application/octet-stream")
                            return
                        with tracer.span("ps/apply", boot=boot):
                            # The buffer-lock + apply + WAL durability
                            # window — the "lock" phase in the per-unit
                            # critical-path table.
                            buffer.apply_delta(tree)
                            if wal_writer is not None:
                                # Durability BEFORE the ack: once the
                                # worker sees this reply, the delta
                                # survives a PS crash (at most
                                # wal_every-1 trailing versions at risk).
                                wal_writer.after_update()
                    self._reply(b"")
                elif path.startswith("/heartbeat/"):
                    detector.beat(path[len("/heartbeat/"):])
                    self._reply(b"ok")
                elif path.startswith("/deregister/"):
                    detector.deregister(path[len("/deregister/"):])
                    self._reply(b"ok")
                elif path.startswith("/barrier/"):
                    self._reply(str(barriers.arrive(path[len("/barrier/"):])).encode())
                else:
                    self.send_error(404)

        self._httpd = _TrackingHTTPServer((self.host, self.port), Handler)
        if self.port == 0:  # ephemeral port (tests)
            self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        self._mount_ops("http")

    def stop(self) -> None:
        self._unmount_ops()
        self._close_store("close")
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self.wal_writer is not None:
            self.wal_writer.sync()  # clean shutdown leaves zero WAL lag

    def kill(self) -> None:
        """Simulate a crash: stop accepting, sever in-flight connections,
        and — unlike ``stop`` — do NOT sync the WAL. What survives is
        exactly what ``after_update`` already made durable, which is the
        contract chaos tests exercise. The flight recorder IS dumped
        (``flight_dump``): a real crash handler would do the same from a
        signal/atexit hook, and the post-mortem needs the anomaly ring
        precisely when the shutdown was unclean."""
        if self._httpd is not None:
            # Go dark FIRST: a crash is atomic from the clients' side,
            # and recording before the sever would let late pushes keep
            # landing while the flight dump + journal close run. Sever
            # before shutdown() — shutdown blocks on the serve loop's
            # poll interval, and handler threads keep acking during it.
            self._httpd.sever_all()
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._record_kill()
            self._unmount_ops()

    def get_parameters(self):
        return self.buffer.get()

    @property
    def master_url(self) -> str:
        return socket_utils.determine_master(self.port)

    def client(self):
        from elephas_tpu.parameter.client import HttpClient

        return HttpClient(
            f"{_dial_host(self.host)}:{self.port}", auth_key=self.auth_key
        )


class _SocketHandler(socketserver.BaseRequestHandler):
    def handle(self):
        # Nagle + delayed-ACK turns every small frame (12-byte
        # not-modified replies, acks, shard-info) into a ~40 ms stall;
        # the protocol is strict request/reply, so coalescing buys
        # nothing.
        self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        buffer = self.server.buffer  # type: ignore[attr-defined]
        barriers = self.server.barriers  # type: ignore[attr-defined]
        key = self.server.auth_key  # type: ignore[attr-defined]
        guard = self.server.replay_guard  # type: ignore[attr-defined]
        cache = self.server.cache  # type: ignore[attr-defined]
        boot = self.server.boot  # type: ignore[attr-defined]
        detector = self.server.detector  # type: ignore[attr-defined]
        wal_writer = self.server.wal_writer  # type: ignore[attr-defined]
        tracer_of = self.server.tracer_of  # type: ignore[attr-defined]
        ledger = self.server.ledger  # type: ignore[attr-defined]
        admission = self.server.admission  # type: ignore[attr-defined]
        shard_info = self.server.shard_info  # type: ignore[attr-defined]
        cache_hits, bytes_tx, bytes_rx = _ps_counters("socket")
        try:
            while True:
                # With auth_key set, receive() verifies the frame's HMAC
                # and replay-freshness BEFORE any payload decode (pickle
                # OR packed); a bad tag or a replayed nonce raises
                # ConnectionError and the connection closes without
                # touching the buffer. Replies are MAC-bound to the
                # request's nonce (advisor r4) so a captured response
                # can't be replayed into a later exchange — the client
                # verifies with the nonce it sent.
                obj, req_nonce = socket_utils.receive(
                    self.request, key=key, replay_guard=guard, return_nonce=True
                )

                def reply(obj):
                    if isinstance(obj, socket_utils.RawPayload):
                        bytes_tx.inc(obj.nbytes)
                    socket_utils.send(self.request, obj, key=key, bind=req_nonce)

                # A raw (non-pickled) payload is a packed-codec PUSH:
                # the frame body IS the delta, sent without a pickle
                # wrapper so the server decodes it zero-copy. The frame's
                # own "tc" header carries the pusher's trace context —
                # adopt it so the handle/apply spans join the worker's
                # unit trace across the socket.
                if isinstance(obj, (bytes, bytearray, memoryview)):
                    mv = memoryview(obj)
                    bytes_rx.inc(mv.nbytes)
                    tree, tc, seen, worker, syncint = wire.decode_push(mv)
                    tracer = tracer_of()
                    rejected_frame = None
                    with obs.activate(_as_trace_ctx(tc)), tracer.span(
                            "ps/handle_push", boot=boot,
                            transport="socket") as hsp:
                        lag = _lag_of(buffer, seen)
                        if hsp and lag is not None:
                            hsp.note(staleness=lag, worker=worker)
                        verdict, tree = _admit(admission, ledger, worker,
                                               lag, tree, "socket", hsp)
                        if verdict != "reject":
                            # Applied pushes only — see the HTTP path.
                            _note_staleness(ledger, worker, lag, seen,
                                            mv.nbytes,
                                            sync_interval=syncint)
                        if verdict == "reject":
                            # Typed refusal (only ever sent to stamped
                            # peers — legacy pushes always accept).
                            rejected_frame = wire.encode_rejected(
                                buffer.version, lag,
                                admission.max_staleness)
                        else:
                            with tracer.span("ps/apply", boot=boot):
                                buffer.apply_delta(tree)
                                if wal_writer is not None:
                                    wal_writer.after_update()  # durable pre-ack
                    reply(rejected_frame if rejected_frame is not None
                          else b"ok")
                    continue

                # Frames are (kind, payload) from legacy peers or
                # (kind, payload, trace_ctx) from tracing ones — the
                # optional third element never changes dispatch.
                kind, payload, *rest = obj
                ctx = _as_trace_ctx(rest[0]) if rest else None
                if kind == "g":  # legacy pull → cached pickle snapshot
                    with obs.activate(ctx), tracer_of().span(
                            "ps/handle_pull", boot=boot, transport="socket"):
                        _, snap = cache.frames("pickle")
                        reply(socket_utils.RawPayload([snap]))
                elif kind == "G":
                    # Packed pull; payload is the client's last-seen
                    # position — ``(boot, version)`` from resilient
                    # clients, a bare int from pre-boot-id peers. A bare
                    # version can alias a previous server life after warm
                    # restart, so it NEVER earns a not-modified reply
                    # (full body instead — correct, just uncached).
                    with obs.activate(ctx), tracer_of().span(
                            "ps/handle_pull", boot=boot, transport="socket"):
                        version, frames = cache.frames("packed")
                        if (isinstance(payload, (tuple, list))
                                and len(payload) == 2
                                and payload[0] == boot
                                and payload[1] == version):
                            cache_hits.inc()
                            reply(wire.encode_not_modified(version))
                        else:
                            reply(frames)
                elif kind == "u":
                    tracer = tracer_of()
                    with obs.activate(ctx), tracer.span(
                            "ps/handle_push", boot=boot, transport="socket"):
                        # Legacy pickle frame: no staleness stamps — the
                        # ledger counts it as unstamped coverage.
                        _note_staleness(ledger, None, None, None, 0)
                        with tracer.span("ps/apply", boot=boot):
                            buffer.apply_delta(payload)
                            if wal_writer is not None:
                                wal_writer.after_update()  # durable pre-ack
                    reply(b"ok")
                elif kind == "h":  # heartbeat: payload = worker id
                    detector.beat(str(payload))
                    reply(b"ok")
                elif kind == "m":  # membership table (sweeps first)
                    reply(detector.membership())
                elif kind == "d":  # deregister: payload = worker id
                    detector.deregister(str(payload))
                    reply(b"ok")
                elif kind == "b":  # barrier arrive(tag) -> count
                    reply(barriers.arrive(payload))
                elif kind == "c":  # barrier count(tag)
                    reply(barriers.count(payload))
                elif kind == "i":  # shard-group handshake (live boot)
                    reply(dict(shard_info, boot=boot)
                          if shard_info is not None else None)
                elif kind == "V":  # version-PINNED pull (rollout plane)
                    with obs.activate(ctx), tracer_of().span(
                            "ps/handle_pull", boot=boot,
                            transport="socket"):
                        reply(_pinned_payload(cache, wal_writer,
                                              int(payload)))
                else:
                    break
        except (ConnectionError, OSError):
            pass


class _ConnectionTracker:
    """socketserver mixin remembering live connections so a simulated crash
    (``SocketServer.kill`` / ``HttpServer.kill``) can sever them.

    ``shutdown()`` alone only stops the acceptor loop: persistent client
    connections keep being served by their (daemon) handler threads, which
    is NOT what a dead process looks like from the worker's side. Chaos
    tests need the worker to actually observe broken pipes and
    connection-refused, so ``sever_all`` force-closes every tracked
    connection."""

    def __init__(self, *args, **kwargs):
        self._live_conns: set = set()
        self._conns_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._conns_lock:
            self._live_conns.add(request)
        super().process_request(request, client_address)

    def close_request(self, request):
        with self._conns_lock:
            self._live_conns.discard(request)
        super().close_request(request)

    def sever_all(self) -> None:
        with self._conns_lock:
            conns = list(self._live_conns)
            self._live_conns.clear()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


class _TrackingHTTPServer(_ConnectionTracker, ThreadingHTTPServer):
    daemon_threads = True


class _ThreadingTCPServer(_ConnectionTracker, socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class SocketServer(_ObservableServerMixin, BaseParameterServer):
    """Raw-TCP transport (reference ``SocketServer``): persistent
    connections carrying ``('g', None)`` / ``('u', delta)`` frames."""

    def __init__(
        self,
        params,
        lock: bool = True,
        port: int = 4000,
        device: Optional[jax.Device] = None,
        host: Optional[str] = None,
        granularity: str = "tree",
        auth_key: Optional[bytes] = None,
        wal_dir: Optional[str] = None,
        wal_every: int = 1,
        wal_keep: int = 3,
        heartbeat_timeout: Optional[float] = None,
        tracer=None,
        ops_port: Optional[int] = None,
        role: str = "ps",
        shard_info: Optional[dict] = None,
        max_staleness: Optional[int] = None,
        staleness_soft: Optional[int] = None,
        store_dir: Optional[str] = "auto",
    ):
        """``auth_key``: shared HMAC-SHA256 secret — every frame in both
        directions carries a tag (nonce+timestamp under the MAC) verified
        before unpickling, and the server rejects replayed/stale nonces
        (see ``utils.sockets.send/receive``/``ReplayGuard``).
        ``wal_dir``/``wal_every``/``heartbeat_timeout``/``tracer``/
        ``ops_port``/``role``/``shard_info``/``max_staleness``/
        ``staleness_soft``/``store_dir``: see ``HttpServer`` —
        identical durability,
        liveness, observability, shard-group handshake, and staleness
        admission semantics (here the rejection reply is the raw
        ``EPRJ`` frame in place of the ``b"ok"`` ack)."""
        self.buffer = ParameterBuffer(params, lock=lock, device=device,
                                      granularity=granularity)
        self.host = host if host is not None else _default_bind_host()
        self.port = port
        self.auth_key = auth_key
        self.replay_guard = socket_utils.ReplayGuard() if auth_key else None
        self.barriers = _BarrierBook()
        self.boot = _new_boot_id()
        self.detector = _make_detector(heartbeat_timeout)
        self.wal_writer = (
            _attach_wal(self.buffer, wal_dir, wal_every, wal_keep=wal_keep)
            if wal_dir else None
        )
        self.tracer = tracer
        self.ops_port = ops_port
        self.ops = None
        # See HttpServer: staleness ledger + SLO alert engine + admission.
        self.ledger = obs.StalenessLedger()
        self.alerts = obs.AlertEngine()
        self.admission = AdmissionPolicy(max_staleness, staleness_soft)
        self.role = role
        self.shard_info = shard_info
        self.flight_dump: Optional[str] = None
        self._wal_dir = wal_dir
        self._attach_telemetry_store(store_dir)
        self._server = None
        self._thread = None

    def start(self) -> None:
        self._server = _ThreadingTCPServer((self.host, self.port), _SocketHandler)
        self._server.buffer = self.buffer  # type: ignore[attr-defined]
        self._server.cache = _SnapshotCache(self.buffer, boot=self.boot)  # type: ignore[attr-defined]
        self._server.barriers = self.barriers  # type: ignore[attr-defined]
        self._server.auth_key = self.auth_key  # type: ignore[attr-defined]
        self._server.replay_guard = self.replay_guard  # type: ignore[attr-defined]
        self._server.boot = self.boot  # type: ignore[attr-defined]
        self._server.detector = self.detector  # type: ignore[attr-defined]
        self._server.wal_writer = self.wal_writer  # type: ignore[attr-defined]
        self._server.tracer_of = self._tracer  # type: ignore[attr-defined]
        self._server.ledger = self.ledger  # type: ignore[attr-defined]
        self._server.admission = self.admission  # type: ignore[attr-defined]
        self._server.shard_info = self.shard_info  # type: ignore[attr-defined]
        if self.port == 0:
            self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        self._mount_ops("socket")

    def stop(self) -> None:
        self._unmount_ops()
        self._close_store("close")
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self.wal_writer is not None:
            self.wal_writer.sync()  # clean shutdown leaves zero WAL lag

    def kill(self) -> None:
        """Simulate a crash: sever live connections (persistent socket
        clients would otherwise keep being served by their handler
        threads) and skip the clean-shutdown WAL sync — durability after
        a kill is exactly what ``after_update`` already flushed. The
        flight recorder IS dumped first (``flight_dump``) — the
        post-mortem artifact a real crash handler would emit."""
        if self._server is not None:
            # Sever first (crash is atomic to clients; shutdown() alone
            # blocks on the poll interval while handlers keep acking),
            # record after — the ps_kill note carries the frozen version.
            self._server.sever_all()
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._record_kill()
            self._unmount_ops()

    def get_parameters(self):
        return self.buffer.get()

    def client(self):
        from elephas_tpu.parameter.client import SocketClient

        return SocketClient(
            f"{_dial_host(self.host)}:{self.port}", auth_key=self.auth_key
        )


def make_server(
    mode: str,
    params,
    lock: bool = True,
    port: int = 4000,
    device: Optional[jax.Device] = None,
    host: Optional[str] = None,
    granularity: str = "tree",
    auth_key: Optional[bytes] = None,
    wal_dir: Optional[str] = None,
    wal_every: int = 1,
    wal_keep: int = 3,
    heartbeat_timeout: Optional[float] = None,
    tracer=None,
    ops_port: Optional[int] = None,
    role: str = "ps",
    shard_info: Optional[dict] = None,
    max_staleness: Optional[int] = None,
    staleness_soft: Optional[int] = None,
    store_dir: Optional[str] = "auto",
) -> BaseParameterServer:
    """Factory keyed on the reference's ``parameter_server_mode``.
    ``granularity`` ('tree'|'leaf') sets the hogwild apply isolation —
    see ``ParameterBuffer``'s memory-model note. ``auth_key`` turns on
    HMAC wire authentication for the http/socket transports.
    ``wal_dir``/``wal_every`` make accepted pushes durable and enable
    warm restart (wire transports only — a local server shares the
    workers' process, so any crash that needs the WAL also killed the
    training job the WAL would resume into). ``tracer``/``ops_port``:
    server-side handle spans and the mountable ops endpoint (wire
    transports; the local server shares the workers' process-global
    tracer already). ``role``/``shard_info``: the fleet role stamp and
    shard-group handshake doc (``parameter.group`` passes these; a
    standalone server keeps the defaults).
    ``max_staleness``/``staleness_soft``: bounded-staleness admission
    (wire transports only — a local client applies in-process under the
    buffer lock, so its deltas are never stale). ``store_dir``: durable
    telemetry journal (wire transports; ``"auto"`` mounts next to the
    WAL — see ``HttpServer``)."""
    if mode == "local":
        if wal_dir is not None:
            raise ValueError(
                "wal_dir requires a wire transport (http|socket): the local "
                "server dies with the training process it would be "
                "restarted for"
            )
        if shard_info is not None:
            raise ValueError(
                "shard_info requires a wire transport (http|socket): shard "
                "group members are separate server processes"
            )
        if max_staleness is not None or staleness_soft is not None:
            raise ValueError(
                "staleness admission requires a wire transport "
                "(http|socket): local pushes apply under the buffer lock "
                "and are never stale"
            )
        if store_dir not in (None, "auto"):
            raise ValueError(
                "store_dir requires a wire transport (http|socket): the "
                "local server's telemetry dies with the training process "
                "a post-mortem would reconstruct"
            )
        return LocalServer(params, lock=lock, device=device, granularity=granularity,
                           heartbeat_timeout=heartbeat_timeout)
    if mode == "http":
        return HttpServer(params, lock=lock, port=port, device=device, host=host,
                          granularity=granularity, auth_key=auth_key,
                          wal_dir=wal_dir, wal_every=wal_every,
                          wal_keep=wal_keep,
                          heartbeat_timeout=heartbeat_timeout,
                          tracer=tracer, ops_port=ops_port,
                          role=role, shard_info=shard_info,
                          max_staleness=max_staleness,
                          staleness_soft=staleness_soft,
                          store_dir=store_dir)
    if mode == "socket":
        return SocketServer(params, lock=lock, port=port, device=device, host=host,
                            granularity=granularity, auth_key=auth_key,
                            wal_dir=wal_dir, wal_every=wal_every,
                            wal_keep=wal_keep,
                            heartbeat_timeout=heartbeat_timeout,
                            tracer=tracer, ops_port=ops_port,
                            role=role, shard_info=shard_info,
                            max_staleness=max_staleness,
                            staleness_soft=staleness_soft,
                            store_dir=store_dir)
    raise ValueError(f"parameter_server_mode must be local|http|socket, got {mode!r}")
