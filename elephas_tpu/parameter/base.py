"""Abstract parameter server/client interfaces.

Reference: ``elephas/parameter/base.py::{BaseParameterServer,
BaseParameterClient}`` (SURVEY.md §2.1 "PS servers"/"PS clients" rows).
"""

from __future__ import annotations

import abc


class BaseParameterServer(abc.ABC):
    """Central weight store for async/hogwild modes."""

    @abc.abstractmethod
    def start(self) -> None:
        """Begin serving (no-op for in-process stores)."""

    @abc.abstractmethod
    def stop(self) -> None:
        """Stop serving and release resources."""

    @abc.abstractmethod
    def get_parameters(self):
        """Current weights as a pytree (server-side view)."""

    @abc.abstractmethod
    def client(self) -> "BaseParameterClient":
        """A client wired to this server (in-process or via its transport)."""


class BaseParameterClient(abc.ABC):
    """Worker-side pull/push of weights and deltas."""

    @abc.abstractmethod
    def get_parameters(self):
        """Pull current weights."""

    @abc.abstractmethod
    def update_parameters(self, delta) -> None:
        """Push a weight delta (``before - after``; server applies
        ``weights -= delta``, matching the reference's convention)."""

    # --- liveness / control-plane surface (resilience layer) -----------
    # Benign defaults so in-process clients and test fakes stay minimal;
    # the wire clients override these with real PS round-trips.

    def heartbeat(self, worker_id: str) -> None:
        """Tell the PS failure detector this worker is alive."""

    def membership(self) -> dict:
        """The PS failure detector's worker table (id -> state info)."""
        return {}

    def deregister(self, worker_id: str) -> None:
        """Graceful exit: drop the worker from the failure detector so a
        clean shutdown is never counted as an expiry."""

    def health(self) -> bool:
        """Would a new request reach the server right now?"""
        return True

    def shard_info(self):
        """The server's shard-group identity (``{digest, shard, k,
        boot}``), or None from a standalone (unsharded) server. The
        sharded client's handshake verifies this before any transfer."""
        return None

    def close(self) -> None:
        """Release any pooled transport state (idempotent)."""
