"""Abstract parameter server/client interfaces.

Reference: ``elephas/parameter/base.py::{BaseParameterServer,
BaseParameterClient}`` (SURVEY.md §2.1 "PS servers"/"PS clients" rows).
"""

from __future__ import annotations

import abc


class BaseParameterServer(abc.ABC):
    """Central weight store for async/hogwild modes."""

    @abc.abstractmethod
    def start(self) -> None:
        """Begin serving (no-op for in-process stores)."""

    @abc.abstractmethod
    def stop(self) -> None:
        """Stop serving and release resources."""

    @abc.abstractmethod
    def get_parameters(self):
        """Current weights as a pytree (server-side view)."""

    @abc.abstractmethod
    def client(self) -> "BaseParameterClient":
        """A client wired to this server (in-process or via its transport)."""


class BaseParameterClient(abc.ABC):
    """Worker-side pull/push of weights and deltas."""

    @abc.abstractmethod
    def get_parameters(self):
        """Pull current weights."""

    @abc.abstractmethod
    def update_parameters(self, delta) -> None:
        """Push a weight delta (``before - after``; server applies
        ``weights -= delta``, matching the reference's convention)."""
