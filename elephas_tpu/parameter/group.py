"""Sharded parameter-server group with a WAL-streamed hot standby tier.

One PS process is the aggregate-bandwidth ceiling for a large worker
fleet (PROFILE.md §14), and failover on a single server is a cold warm
restart. This module scales the PS horizontally and makes failover a
*promotion*:

- ``ShardPlan`` — a deterministic partition of the parameter tree across
  K server processes. The partition key is the packed wire codec's
  per-leaf header row (dtype, shape, nbytes): leaves are bin-packed by
  payload bytes, largest first, so each shard carries a near-equal slice
  of the wire traffic. The plan is pinned by a **shard-map digest**; the
  client/server handshake verifies it, so a client holding a stale plan
  gets a typed ``ShardMapMismatch`` instead of silently merging the
  wrong leaves.
- ``ShardedParameterClient`` — scatters pushes and gathers pulls across
  the shards concurrently. Each shard is an unmodified wire server over
  a *flat path-keyed sub-tree*, so the per-shard version-gated
  not-modified cache (PR 4) and the ``sv``/``wk`` staleness stamps
  (PR 7) keep working shard-by-shard with zero new wire formats.
- ``WalStreamer`` + ``ShardGroup`` — each primary's ``SnapshotWAL``
  (PR 5) is tailed into a warm spare's buffer. When the group's
  ``FailureDetector`` declares a primary dead, the spare is promoted:
  final WAL catch-up, ``start()``, directory re-publish. The dead
  primary's boot id is **fenced** — a zombie that comes back serving its
  old boot fails the handshake — and the promoted server's fresh boot id
  invalidates every client's not-modified cache for that shard, exactly
  the (boot, version) gating warm restarts already rely on.

Group membership is a ``GroupDirectory``: a generation-counted
shard → address map. Clients re-resolve on a generation bump (failover),
so a promotion is visible as one reconnect, not a config push.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elephas_tpu import obs
from elephas_tpu.parameter import wire
from elephas_tpu.parameter.base import BaseParameterClient, BaseParameterServer
from elephas_tpu.parameter.client import (
    ParameterServerUnavailable,
    make_client,
)
from elephas_tpu.parameter.server import _dial_host, make_server

__all__ = [
    "FencedPrimaryError",
    "GroupDirectory",
    "ShardGroup",
    "ShardGroupError",
    "ShardMapMismatch",
    "ShardPlan",
    "ShardedParameterClient",
    "WalStreamer",
]


class ShardGroupError(RuntimeError):
    """Base class for shard-group protocol errors."""


class ShardMapMismatch(ShardGroupError):
    """Client and server disagree on the shard plan (digest/slot) — a
    stale plan must be a typed error, never a silently mis-merged tree."""


class FencedPrimaryError(ShardGroupError):
    """The dialed server is a fenced (pre-failover) primary — a zombie
    that must not receive writes; re-resolve through the directory."""


def _shard_failover_counter():
    return obs.default_registry().counter(
        "ps_shard_failover_total",
        "standby promotions after a shard primary was declared dead",
    )


def _standby_lag_gauge():
    return obs.default_registry().gauge(
        "ps_standby_lag_snapshots",
        "durable WAL snapshot versions a shard's hot standby has not "
        "yet applied",
        labelnames=("shard",),
    )


# -- shard plan ---------------------------------------------------------------


def _leaf_paths(obj, prefix: Tuple[str, ...], out: List[str]) -> None:
    """Leaf paths in EXACTLY ``wire._build_skeleton``'s traversal order
    (dict insertion order, depth-first), so path i names header row i."""
    if obj is None:
        return
    if isinstance(obj, dict):
        for key, val in obj.items():
            _leaf_paths(val, prefix + (str(key),), out)
        return
    if isinstance(obj, (list, tuple)):
        for i, val in enumerate(obj):
            _leaf_paths(val, prefix + (str(i),), out)
        return
    out.append("/".join(prefix))


class ShardPlan:
    """Deterministic K-way partition of a parameter tree.

    ``build`` enumerates the tree with the packed codec's own skeleton
    walk, computes each leaf's wire header row, and greedily bin-packs
    leaves onto shards by payload bytes (largest first; ties broken by
    path, then by shard index) — the same inputs always produce the same
    plan, on any host. Each shard's store is a FLAT ``{path: leaf}``
    dict, which is itself a valid packed-codec tree: every shard server
    is an unmodified ``HttpServer``/``SocketServer`` with its full cache
    /WAL/staleness machinery intact.
    """

    __slots__ = ("k", "paths", "rows", "shard_of", "_skeleton")

    def __init__(self, k: int, paths: List[str], rows: List[list],
                 shard_of: List[int], skeleton):
        self.k = k
        self.paths = paths
        self.rows = rows          # per-leaf [dtype, shape, nbytes]
        self.shard_of = shard_of  # leaf index -> shard index
        self._skeleton = skeleton

    @classmethod
    def build(cls, tree, k: int) -> "ShardPlan":
        if k < 1:
            raise ValueError(f"shard count must be >= 1, got {k}")
        leaves: List[Any] = []
        try:
            skeleton = wire._build_skeleton(tree, leaves)
        except wire.WireFormatError as exc:
            raise ShardGroupError(
                f"shard plan needs a packed-codec-compatible tree: {exc}"
            ) from exc
        paths: List[str] = []
        _leaf_paths(tree, (), paths)
        if len(paths) != len(leaves):  # defensive: walks must agree
            raise ShardGroupError(
                f"path walk found {len(paths)} leaves but the codec "
                f"skeleton found {len(leaves)}"
            )
        if len(set(paths)) != len(paths):
            raise ShardGroupError(
                "parameter tree has colliding leaf paths (e.g. dict keys "
                "0 and '0' at one level) — cannot shard by path"
            )
        if k > len(leaves):
            raise ValueError(
                f"cannot spread {len(leaves)} leaves over {k} shards "
                "(every shard must own at least one leaf)"
            )
        rows = []
        for leaf in leaves:
            arr = np.ascontiguousarray(leaf)
            if arr.dtype == object:
                raise ShardGroupError("object-dtype leaf has no wire layout")
            rows.append([np.asarray(leaf).dtype.name,
                         list(np.shape(leaf)), int(arr.nbytes)])
        # Greedy longest-processing-time: biggest leaf onto the lightest
        # shard. Ties in size break by path; ties in load by shard index.
        order = sorted(range(len(leaves)),
                       key=lambda i: (-rows[i][2], paths[i]))
        loads = [0] * k
        shard_of = [0] * len(leaves)
        for i in order:
            shard = min(range(k), key=lambda s: (loads[s], s))
            shard_of[i] = shard
            loads[shard] += rows[i][2]
        return cls(k, paths, rows, shard_of, skeleton)

    @property
    def digest(self) -> str:
        """Content hash of the full plan — partition key AND placement.
        The client/server handshake compares this, so any drift (plan
        built from a different tree, different K, different balancer)
        is a typed error before a single leaf moves. Entries are sorted
        by path: two plans over the same tree hash identically even if
        one was built from a sorted-key copy (jax tree ops rebuild
        dicts in sorted order; the balancer is order-insensitive too)."""
        doc = [self.k, sorted([p, s, r] for p, s, r
                              in zip(self.paths, self.shard_of, self.rows))]
        blob = json.dumps(doc, separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def bytes_per_shard(self) -> List[int]:
        loads = [0] * self.k
        for i, shard in enumerate(self.shard_of):
            loads[shard] += self.rows[i][2]
        return loads

    def shard_paths(self, shard: int) -> List[str]:
        return [p for p, s in zip(self.paths, self.shard_of) if s == shard]

    def split(self, tree) -> List[Dict[str, Any]]:
        """The K flat ``{path: leaf}`` sub-trees of ``tree``.

        Keyed by the GIVEN tree's own path walk, never positionally
        against the plan's build order: jax tree ops (``tree_map``,
        jitted subtracts) rebuild dicts in sorted-key order, so a delta
        computed from a pulled tree legitimately carries the same paths
        in a different traversal order. An unknown or missing path —
        a genuinely different tree — is a ``ShardMapMismatch``."""
        leaves: List[Any] = []
        paths: List[str] = []
        wire._build_skeleton(tree, leaves)
        _leaf_paths(tree, (), paths)
        if len(paths) != len(leaves):
            raise ShardGroupError(
                f"path walk found {len(paths)} leaves but the codec "
                f"skeleton found {len(leaves)}"
            )
        if set(paths) != set(self.paths):
            unknown = sorted(set(paths) - set(self.paths))[:3]
            missing = sorted(set(self.paths) - set(paths))[:3]
            raise ShardMapMismatch(
                f"tree does not match the shard plan (digest "
                f"{self.digest}): unknown leaves {unknown}, missing "
                f"leaves {missing}"
            )
        shard_by_path = dict(zip(self.paths, self.shard_of))
        out: List[Dict[str, Any]] = [{} for _ in range(self.k)]
        for path, leaf in zip(paths, leaves):
            out[shard_by_path[path]][path] = leaf
        return out

    def shard_tree(self, tree, shard: int) -> Dict[str, Any]:
        return self.split(tree)[shard]

    def merge(self, shard_trees: List[Dict[str, Any]]):
        """Reassemble the full tree from the K flat sub-trees (inverse
        of ``split``; raises ``ShardMapMismatch`` on a missing leaf)."""
        leaves: List[Any] = []
        for i, path in enumerate(self.paths):
            sub = shard_trees[self.shard_of[i]]
            if path not in sub:
                raise ShardMapMismatch(
                    f"shard {self.shard_of[i]} reply is missing leaf "
                    f"{path!r} — stale shard map?"
                )
            leaves.append(sub[path])
        return wire._restore_skeleton(self._skeleton, leaves)

    def describe(self) -> Dict[str, Any]:
        return {"k": self.k, "digest": self.digest,
                "leaves": len(self.paths),
                "bytes_per_shard": self.bytes_per_shard()}


# -- directory ----------------------------------------------------------------


class GroupDirectory:
    """Generation-counted shard → (address, boot) map plus the fence set.

    The group's single source of truth for "who serves shard i right
    now". A promotion bumps ``generation``; sharded clients compare the
    generation per call and re-dial on a bump — re-resolution is one
    integer check on the hot path. ``fence`` records the boot ids of
    dead primaries; the handshake rejects a server presenting a fenced
    boot (the zombie that never noticed it was declared dead).
    """

    def __init__(self, digest: str, k: int):
        self.digest = digest
        self.k = k
        self._addresses: Dict[int, str] = {}
        self._boots: Dict[int, str] = {}
        self._fenced: set = set()
        self._generation = 0
        self._lock = threading.Lock()

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def publish(self, shard: int, address: str, boot: str) -> int:
        with self._lock:
            self._addresses[shard] = address
            self._boots[shard] = boot
            self._generation += 1
            return self._generation

    def address_of(self, shard: int) -> str:
        with self._lock:
            try:
                return self._addresses[shard]
            except KeyError:
                raise ShardGroupError(
                    f"no address published for shard {shard}"
                ) from None

    def fence(self, boot: str) -> None:
        with self._lock:
            self._fenced.add(boot)

    def is_fenced(self, boot: Optional[str]) -> bool:
        with self._lock:
            return boot in self._fenced

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"digest": self.digest, "k": self.k,
                    "generation": self._generation,
                    "addresses": dict(self._addresses),
                    "boots": dict(self._boots),
                    "fenced": sorted(self._fenced)}


# -- sharded client -----------------------------------------------------------


class ShardedParameterClient(BaseParameterClient):
    """Scatter/gather client over a K-shard group.

    Holds one wire sub-client per shard (dialed through the directory,
    re-dialed on a generation bump) and runs the K round-trips of every
    pull/push concurrently on a small pool — aggregate bandwidth scales
    with K while each sub-client keeps its own version-gated pull cache
    and staleness stamps.

    Handshake: the first dial to each shard fetches the server's
    ``shard_info`` and verifies (digest, slot, un-fenced boot). A server
    that doesn't present a shard map, presents the wrong digest, or sits
    in the wrong slot raises ``ShardMapMismatch``; a fenced boot raises
    ``FencedPrimaryError`` (and triggers one directory re-resolution —
    the promotion may simply not have reached this client yet).
    """

    def __init__(self, mode: str, directory: GroupDirectory, plan: ShardPlan,
                 auth_key: Optional[bytes] = None,
                 codec: Optional[str] = None,
                 push_quantize: Optional[str] = None):
        if mode not in ("http", "socket"):
            raise ValueError(
                f"sharded client needs a wire transport, got {mode!r}")
        if directory.digest != plan.digest:
            raise ShardMapMismatch(
                f"directory pins digest {directory.digest} but the plan "
                f"is {plan.digest}"
            )
        self._mode = mode
        self._directory = directory
        self._plan = plan
        self._auth_key = auth_key
        self._codec = codec
        self._push_quantize = push_quantize
        self._worker_id: Optional[str] = None
        self._sync_interval: Optional[float] = None
        self._clients: Dict[int, BaseParameterClient] = {}
        self._client_gen = -1
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=plan.k, thread_name_prefix="ps-shard")

    # worker_id is a property so a post-construction stamp (the elastic
    # pool's client factory contract) propagates to every sub-client.
    @property
    def worker_id(self) -> Optional[str]:
        return self._worker_id

    @worker_id.setter
    def worker_id(self, value: Optional[str]) -> None:
        self._worker_id = value
        with self._lock:
            for client in self._clients.values():
                client.worker_id = value

    # Same post-construction propagation for the SYNC-column stamp: the
    # comms pipeline updates it on the pool client, every sub-client's
    # next push carries it.
    @property
    def sync_interval(self) -> Optional[float]:
        return self._sync_interval

    @sync_interval.setter
    def sync_interval(self, value: Optional[float]) -> None:
        self._sync_interval = value
        with self._lock:
            for client in self._clients.values():
                client.sync_interval = value

    @property
    def plan(self) -> ShardPlan:
        return self._plan

    def _verify(self, shard: int, client, address: str) -> None:
        info = client.shard_info()
        if info is None:
            obs.default_flight_recorder().note(
                "shard_map_mismatch", "error",
                shard=shard, address=address, reason="no shard map",
            )
            raise ShardMapMismatch(
                f"server at {address} presented no shard map — is it a "
                "standalone (unsharded) parameter server?"
            )
        if self._directory.is_fenced(info.get("boot")):
            raise FencedPrimaryError(
                f"server at {address} is a fenced primary for shard "
                f"{shard} (boot {info.get('boot')}) — re-resolve"
            )
        if (info.get("digest") != self._plan.digest
                or info.get("shard") != shard):
            obs.default_flight_recorder().note(
                "shard_map_mismatch", "error",
                shard=shard, address=address,
                server_digest=info.get("digest"),
                server_shard=info.get("shard"),
                client_digest=self._plan.digest,
            )
            raise ShardMapMismatch(
                f"shard map mismatch at {address}: server serves shard "
                f"{info.get('shard')} of plan {info.get('digest')}, client "
                f"expected shard {shard} of plan {self._plan.digest}"
            )

    def _client(self, shard: int):
        with self._lock:
            gen = self._directory.generation
            if gen != self._client_gen:
                # Failover re-resolution: one promotion invalidates the
                # whole pool (cheap — K small), and the promoted server's
                # fresh boot id makes the first pull a full body anyway.
                for client in self._clients.values():
                    client.close()
                self._clients.clear()
                self._client_gen = gen
            client = self._clients.get(shard)
            if client is None:
                address = self._directory.address_of(shard)
                client = make_client(
                    self._mode, address, auth_key=self._auth_key,
                    codec=self._codec, push_quantize=self._push_quantize,
                )
                client.worker_id = self._worker_id
                client.sync_interval = self._sync_interval
                try:
                    self._verify(shard, client, address)
                except Exception:
                    client.close()
                    raise
                self._clients[shard] = client
            return client

    def shard_client(self, shard: int):
        """The dialed wire sub-client for ONE shard — the blackbox
        canary's per-shard probe surface (``obs.canary.PSCanary`` times
        a write-read round trip against each shard independently, so a
        single dead primary is attributable). Shares the pool's cache,
        verification, and generation-bump re-dial."""
        if not 0 <= shard < self._plan.k:
            raise ValueError(
                f"shard {shard} outside plan of {self._plan.k}")
        return self._client(shard)

    def _fanout(self, fn, shards: Optional[List[int]] = None) -> List[Any]:
        """Run ``fn(shard, client)`` for every shard concurrently; the
        first failure propagates (after every future settles, so no
        request is abandoned mid-socket)."""
        shards = list(range(self._plan.k)) if shards is None else shards

        def one(shard: int):
            return fn(shard, self._client(shard))

        futures = [self._pool.submit(one, s) for s in shards]
        results, first_exc = [], None
        for fut in futures:
            try:
                results.append(fut.result())
            except Exception as exc:
                if first_exc is None:
                    first_exc = exc
        if first_exc is not None:
            raise first_exc
        return results

    def get_parameters(self):
        with obs.default_tracer().span("ps/gather", shards=self._plan.k):
            subs = self._fanout(lambda s, c: c.get_parameters())
        return self._plan.merge(subs)

    def known_version(self) -> Optional[int]:
        """The group's pulled position: max over the sub-clients' cached
        versions (None before any pull). Shard version lines advance in
        lockstep under full-tree pushes — every ``update_parameters``
        scatters one slice to every shard — so the max IS the group
        version; a lagging shard is surfaced by the next ``pull``'s
        per-shard version check, not hidden by a min()."""
        versions = [
            c.known_version() for c in list(self._clients.values())
            if hasattr(c, "known_version")
        ]
        versions = [v for v in versions if v is not None]
        return max(versions) if versions else None

    def pull(self, version: Optional[int] = None):
        """``(version, tree)`` — the subscription plane's read.

        ``version=None`` is the live read: the normal version-gated
        gather (steady state costs K not-modified frames) plus the
        group position the reply landed at. ``version=`` is the PINNED
        read: every shard answers from its live buffer or its WAL
        history at exactly that version (``get_parameters_pinned``), so
        rollback and A/B reads cannot race ongoing training pushes.
        Raises ``VersionUnavailable`` when any shard has pruned the pin.
        """
        if version is not None:
            pin = int(version)
            with obs.default_tracer().span(
                    "ps/gather_pinned", shards=self._plan.k):
                subs = self._fanout(
                    lambda s, c: c.get_parameters_pinned(pin))
            return pin, self._plan.merge(subs)
        tree = self.get_parameters()
        return self.known_version(), tree

    def update_parameters(self, delta) -> None:
        # Admission is per shard: each member judges its slice against
        # its own version line, so a StaleDeltaRejected from any shard
        # propagates (first exception wins) while fresher shards may
        # already have applied theirs — sound for SGD (a partial delta
        # is just a smaller step) and the client's re-pull resyncs all
        # K sub-caches anyway.
        parts = self._plan.split(delta)
        with obs.default_tracer().span("ps/scatter", shards=self._plan.k):
            self._fanout(lambda s, c: c.update_parameters(parts[s]))

    def heartbeat(self, worker_id: str) -> None:
        # Every shard's detector sees the worker: membership stays
        # consistent no matter which shard the elastic pool polls.
        self._fanout(lambda s, c: c.heartbeat(worker_id))

    def membership(self) -> dict:
        return self._client(0).membership()

    def deregister(self, worker_id: str) -> None:
        self._fanout(lambda s, c: c.deregister(worker_id))

    def health(self) -> bool:
        try:
            return all(self._fanout(lambda s, c: c.health()))
        except (ShardGroupError, ParameterServerUnavailable, OSError):
            return False

    def wait_barrier(self, tag: str, n: int,
                     timeout: Optional[float] = None) -> None:
        # Barriers are control-plane, not sharded state: shard 0 hosts
        # the arrival counters for the whole group.
        self._client(0).wait_barrier(tag, n, timeout=timeout)

    def close(self) -> None:
        with self._lock:
            for client in self._clients.values():
                client.close()
            self._clients.clear()
        self._pool.shutdown(wait=False)


# -- WAL streaming + standby --------------------------------------------------


class WalStreamer:
    """Tail a primary's ``SnapshotWAL`` into a standby's buffer.

    The WAL is file-per-version with atomic renames, so tailing is just
    polling ``latest_version()`` and decoding the newest durable
    snapshot into the spare's ``ParameterBuffer`` — the standby is never
    more than one poll interval plus ``wal_every - 1`` versions behind
    what the primary acked. ``clock``/``sleep`` are injectable so
    promotion lifecycles are testable on a fake clock.
    """

    def __init__(self, wal, buffer, poll_interval: float = 0.05,
                 sleep=time.sleep):
        self._wal = wal
        self._buffer = buffer
        self._poll_interval = poll_interval
        self._sleep = sleep
        self.applied_version: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> Optional[int]:
        """Apply the newest durable snapshot if it is new; returns the
        version applied (None when already current / WAL empty)."""
        latest = self._wal.latest_version()
        if latest is None or latest == self.applied_version:
            return None
        from elephas_tpu.checkpoint.checkpoint import NoCheckpointError

        try:
            version, tree = self._wal.restore_latest()
        except NoCheckpointError:
            return None
        if self.applied_version is not None \
                and version <= self.applied_version:
            return None
        self._buffer.set(tree, version=version)
        self.applied_version = version
        return version

    def lag(self) -> int:
        """Durable snapshots the standby has not applied yet (snapshot
        count, not version delta — honest under sparse ``wal_every``)."""
        return len(self._wal.versions_after(self.applied_version))

    def start(self) -> "WalStreamer":
        if self._thread is not None:
            return self

        def run():
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except OSError:
                    pass  # a mid-prune glob race; next poll sees truth
                self._sleep(self._poll_interval)

        self._thread = threading.Thread(
            target=run, name="wal-streamer", daemon=True)
        self._thread.start()
        return self

    def stop(self, catch_up: bool = True) -> Optional[int]:
        """Stop tailing; with ``catch_up`` (the promotion path) apply
        the newest durable snapshot one final time before returning.
        Returns the standby's applied version — the promotion floor."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if catch_up:
            self.poll_once()
        return self.applied_version


class ShardGroup(BaseParameterServer):
    """K shard primaries + optional warm standbys, one per shard.

    The orchestrator the engines/benches drive: builds the plan, boots
    one wire server per shard over its flat sub-tree (role
    ``ps/shard<i>``), publishes addresses into a ``GroupDirectory``, and
    — with ``standby=1`` — keeps an unstarted spare per shard whose
    buffer a ``WalStreamer`` feeds from the primary's WAL.

    Failure handling: ``check()`` runs one monitor pass — health-probe
    every active primary, beat the group's ``FailureDetector``, and
    promote the spare of any shard the detector sweeps dead (fencing the
    dead primary's boot id first). ``start_monitor()`` runs ``check``
    on a daemon thread; tests drive ``check`` directly on a fake clock.
    """

    def __init__(self, params, k: int, mode: str = "socket",
                 standby: int = 0, wal_root: Optional[str] = None,
                 lock: bool = True, device=None, host: Optional[str] = None,
                 granularity: str = "tree",
                 auth_key: Optional[bytes] = None, wal_every: int = 1,
                 wal_keep: int = 3,
                 heartbeat_timeout: Optional[float] = None,
                 ops_port: Optional[int] = None,
                 suspect_after: float = 0.5,
                 clock=time.monotonic, sleep=time.sleep,
                 stream_poll_interval: float = 0.05,
                 max_staleness: Optional[int] = None,
                 staleness_soft: Optional[int] = None):
        if mode not in ("http", "socket"):
            raise ValueError(
                "a PS group needs a wire transport (http|socket): shards "
                f"are separate server processes, got mode={mode!r}"
            )
        if standby not in (0, 1):
            raise ValueError(
                f"standby must be 0 or 1 (one warm spare per shard), "
                f"got {standby}"
            )
        if standby and wal_root is None:
            raise ValueError(
                "standby=1 streams each primary's WAL to its spare — "
                "pass wal_root= (the per-shard WAL parent directory)"
            )
        from elephas_tpu.resilience.liveness import FailureDetector

        self.plan = ShardPlan.build(params, k)
        self.mode = mode
        self.standby = standby
        self.wal_root = wal_root
        self.auth_key = auth_key
        self.directory = GroupDirectory(self.plan.digest, k)
        self.detector = FailureDetector(
            suspect_after=suspect_after, clock=clock)
        self.promotions: List[Dict[str, Any]] = []
        self._clock = clock
        self._sleep = sleep
        self._stream_poll_interval = stream_poll_interval
        self._monitor: Optional[threading.Thread] = None
        self._monitor_stop = threading.Event()
        self._health_clients: Dict[int, Any] = {}
        self._health_gen = -1
        self._lock = threading.Lock()
        self._started = False

        def build(shard: int, role: str, ops: Optional[int],
                  store_dir: Optional[str] = "auto"):
            wal_dir = (os.path.join(wal_root, f"shard{shard}")
                       if wal_root else None)
            return make_server(
                mode, self.plan.shard_tree(params, shard), lock=lock,
                port=0, device=device, host=host, granularity=granularity,
                auth_key=auth_key, wal_dir=wal_dir, wal_every=wal_every,
                wal_keep=wal_keep,
                heartbeat_timeout=heartbeat_timeout, ops_port=ops,
                role=role,
                shard_info={"digest": self.plan.digest, "shard": shard,
                            "k": k},
                # Every member enforces the same staleness bounds: a
                # sharded push is admitted (or refused) per shard against
                # that shard's own version line.
                max_staleness=max_staleness,
                staleness_soft=staleness_soft,
                store_dir=store_dir,
            )

        def ops_at(offset: int) -> Optional[int]:
            if ops_port is None:
                return None
            return 0 if ops_port == 0 else ops_port + offset

        self._active: List[BaseParameterServer] = [
            build(i, f"ps/shard{i}", ops_at(i)) for i in range(k)
        ]
        # A spare shares its shard's wal_dir (WAL streaming) but must
        # NOT share its telemetry directory: the store's open-time tail
        # healing assumes one live writer per directory, and a spare and
        # its primary are alive at once. Each spare journals under its
        # own ``standby<i>`` slot instead of the "auto" placement.
        self._standbys: List[Optional[BaseParameterServer]] = [
            build(i, "ps/standby", ops_at(k + i),
                  store_dir=os.path.join(wal_root, f"standby{i}",
                                         "telemetry"))
            if standby else None
            for i in range(k)
        ]
        for member in self._active + self._standbys:
            if member is not None:
                # Every member's opsd /shards route serves the group
                # topology doc, so any shard answers "who is the group".
                member.shards_fn = self.snapshot
        self._streamers: List[Optional[WalStreamer]] = [None] * k

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        from elephas_tpu.resilience.wal import SnapshotWAL

        for i, server in enumerate(self._active):
            server.start()
            self.directory.publish(i, self._address(server), server.boot)
            self.detector.beat(f"shard{i}")
        for i, spare in enumerate(self._standbys):
            if spare is None:
                continue
            # A warm spare serves no PS traffic, but its ops endpoint
            # mounts now so the fleet board shows the standby tier.
            spare._mount_ops(self.mode)
            wal = SnapshotWAL(os.path.join(self.wal_root, f"shard{i}"))
            self._streamers[i] = WalStreamer(
                wal, spare.buffer,
                poll_interval=self._stream_poll_interval,
                sleep=self._sleep,
            ).start()
        self._started = True

    @staticmethod
    def _address(server) -> str:
        return f"{_dial_host(server.host)}:{server.port}"

    def stop(self) -> None:
        self.stop_monitor()
        for streamer in self._streamers:
            if streamer is not None:
                streamer.stop(catch_up=False)
        self._streamers = [None] * self.plan.k
        for server in self._active:
            try:
                server.stop()
            except Exception:
                pass
        for spare in self._standbys:
            if spare is not None:
                try:
                    spare.stop()
                except Exception:
                    pass
        with self._lock:
            for client in self._health_clients.values():
                client.close()
            self._health_clients.clear()

    # -- server-compatible surface (engine seam) ----------------------------

    def get_parameters(self):
        """Merged tree straight from the shard buffers (driver-side
        snapshot — validation/checkpoint reads, not the worker path)."""
        return self.plan.merge(
            [server.get_parameters() for server in self._active]
        )

    def client(self) -> ShardedParameterClient:
        return ShardedParameterClient(
            self.mode, self.directory, self.plan, auth_key=self.auth_key)

    def primary(self, shard: int) -> BaseParameterServer:
        return self._active[shard]

    def standby_of(self, shard: int) -> Optional[BaseParameterServer]:
        return self._standbys[shard]

    def streamer_of(self, shard: int) -> Optional[WalStreamer]:
        return self._streamers[shard]

    def kill_primary(self, shard: int) -> None:
        """Chaos surface: crash one primary (no WAL sync, severed
        connections) — what the failure detector then has to notice."""
        self._active[shard].kill()

    # -- failure detection + promotion --------------------------------------

    def _health_client(self, shard: int):
        with self._lock:
            gen = self.directory.generation
            if gen != self._health_gen:
                for client in self._health_clients.values():
                    client.close()
                self._health_clients.clear()
                self._health_gen = gen
            client = self._health_clients.get(shard)
            if client is None:
                client = make_client(
                    self.mode, self.directory.address_of(shard),
                    auth_key=self.auth_key,
                )
                self._health_clients[shard] = client
            return client

    def check(self) -> List[int]:
        """One monitor pass: probe every shard, sweep the detector,
        promote the spares of newly-dead shards. Returns the shard
        indices promoted by THIS pass."""
        for i in range(self.plan.k):
            try:
                alive = self._health_client(i).health()
            except (ShardGroupError, OSError):
                alive = False
            if alive:
                self.detector.beat(f"shard{i}")
        promoted = []
        for worker_id in self.detector.sweep():
            if not str(worker_id).startswith("shard"):
                continue
            shard = int(str(worker_id)[len("shard"):])
            if self.promote(shard):
                promoted.append(shard)
        # Every monitor pass refreshes the standby-lag gauge, so lag is
        # visible even on processes nobody scrapes through /shards.
        self._publish_standby_lag()
        return promoted

    def promote(self, shard: int) -> bool:
        """Promote shard ``shard``'s warm spare to primary.

        Fences the dead primary's boot id (the zombie's shard_info
        handshake fails from now on), stops the streamer with one final
        WAL catch-up (nothing acked-and-durable is lost), starts the
        spare, and re-publishes the directory — clients re-resolve on
        the generation bump and their first pull against the fresh boot
        id is a full body, never an aliased cache hit. Returns False
        when the shard has no spare to promote (the failure stays an
        outage, exactly like the single-PS story).
        """
        spare = self._standbys[shard]
        dead = self._active[shard]
        old_boot = getattr(dead, "boot", None)
        if old_boot is not None:
            self.directory.fence(old_boot)
        if spare is None:
            obs.default_flight_recorder().note(
                "shard_failover", "error", shard=shard,
                old_boot=old_boot, promoted=False,
            )
            return False
        t0 = self._clock()
        streamer = self._streamers[shard]
        caught_up = streamer.stop(catch_up=True) if streamer else None
        self._streamers[shard] = None
        self._standbys[shard] = None
        # The promoted server takes the shard's role before its ops
        # endpoint mounts, so the fleet board shows the new topology.
        spare._unmount_ops()
        spare.role = f"ps/shard{shard}"
        if getattr(spare, "store", None) is not None:
            # The journal survives the remount: re-stamp its role and
            # mark the hat-change, so a post-mortem reads the standby's
            # records and the promoted primary's as one process story.
            spare.store.set_role(spare.role)
            spare.store.record_lifecycle(
                "promoted", shard=shard, old_boot=old_boot)
        try:
            dead.stop()  # a crashed server no-ops; a live one is demoted
        except Exception:
            pass
        spare.start()
        self._active[shard] = spare
        self.directory.publish(shard, self._address(spare), spare.boot)
        self.detector.beat(f"shard{shard}")
        promote_s = self._clock() - t0
        record = {
            "shard": shard, "old_boot": old_boot, "new_boot": spare.boot,
            "version": spare.buffer.version, "caught_up_version": caught_up,
            "promote_s": promote_s,
        }
        self.promotions.append(record)
        _shard_failover_counter().inc()
        flight = obs.default_flight_recorder()
        flight.note("shard_failover", "error", shard=shard,
                    old_boot=old_boot, promoted=True)
        flight.note("standby_promoted", "info", shard=shard,
                    boot=spare.boot, version=spare.buffer.version,
                    promote_s=promote_s)
        return True

    def start_monitor(self, interval: float = 0.2) -> None:
        if self._monitor is not None:
            return
        self._monitor_stop.clear()

        def run():
            while not self._monitor_stop.is_set():
                try:
                    self.check()
                except Exception:
                    pass  # the monitor must outlive one bad probe
                self._sleep(interval)

        self._monitor = threading.Thread(
            target=run, name="ps-group-monitor", daemon=True)
        self._monitor.start()

    def stop_monitor(self) -> None:
        if self._monitor is None:
            return
        self._monitor_stop.set()
        self._monitor.join(timeout=5)
        self._monitor = None

    def _publish_standby_lag(self) -> List[Dict[str, Any]]:
        """Per-standby WAL lag, mirrored into the registry gauge
        ``ps_standby_lag_snapshots{shard=}`` (``ps_`` prefix → sampled
        into history rings wherever a sampler runs). A promoted or
        never-staffed shard has no streamer and reports ``None`` —
        the gauge pins 0 there rather than holding a stale lag."""
        out = []
        gauge = _standby_lag_gauge()
        for i, spare in enumerate(self._standbys):
            streamer = self._streamers[i]
            lag = streamer.lag() if streamer else None
            gauge.labels(shard=str(i)).set(float(lag or 0))
            out.append({
                "shard": i,
                "warm": spare is not None,
                "applied_version": (streamer.applied_version
                                    if streamer else None),
                "lag": lag,
            })
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Introspection doc for the opsd ``/shards`` route."""
        return {
            "plan": self.plan.describe(),
            "directory": self.directory.snapshot(),
            "standbys": self._publish_standby_lag(),
            "promotions": list(self.promotions),
        }
