"""HBM-resident parameter buffer — the async/hogwild weight store.

This replaces the reference's Flask/socket parameter server *state*
(``elephas/parameter/server.py::HttpServer``'s weight list guarded by
``RWLock`` — SURVEY.md §2.1, §5.2): the canonical weights live as
``jax.Array``s on a designated device (HBM on TPU), and applying a delta
is a jitted on-device subtract — the weights never bounce through host
memory on the single-host path.

Locking discipline (SURVEY.md §2.2):
- ``lock=True``  (asynchronous): writer-preferring RWLock around
  pull/apply — Downpour SGD with consistent snapshots.
- ``lock=False`` (hogwild): ``NullLock``; pulls may interleave with
  applies. The CPython GIL still makes each pointer swap atomic, so
  "race" means stale/interleaved pytree reads — the Hogwild! contract,
  not corruption (the reference's memory-model difference, documented).

Hogwild memory model, quantified: ``apply_delta`` is a whole-pytree
read-modify-write, so without the lock a concurrent apply that read the
same snapshot overwrites it and the EARLIER delta is dropped entirely —
coarser than Hogwild!'s per-coordinate races (the reference's lock-free
server mutates one shared list in place, losing at most per-element
increments). Measured applied-update fraction under deliberate 8-thread
contention (``tests/test_hogwild_races.py``): **≈0.70** (0.3–0.9 across
runs; jitted CPU apply). Values are never torn — survivors are exact
sums of whole deltas — and the ``version`` counter counts attempts, so
the loss rate is observable as ``1 - applied/version``. Training still
converges (``tests/test_spark_model.py`` hogwild paths) because dropped
deltas are unbiased; use ``lock=True`` when every update must land.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

from elephas_tpu.utils.functional_utils import subtract_params
from elephas_tpu.utils.rwlock import NullLock, RWLock


class ParameterBuffer:
    def __init__(self, params, lock: bool = True, device: Optional[jax.Device] = None):
        self._device = device if device is not None else jax.devices()[0]
        self._params = jax.device_put(params, self._device)
        self._lock = RWLock() if lock else NullLock()
        self._apply = jax.jit(subtract_params)
        self._version = 0
        self._version_guard = threading.Lock()

    @property
    def device(self) -> jax.Device:
        return self._device

    @property
    def version(self) -> int:
        """Number of ATTEMPTED updates (staleness tests / diagnostics).

        Under ``lock=False`` attempts can overwrite each other, so the
        applied count can be lower — see the module docstring's
        lost-update note."""
        return self._version

    def get(self):
        """Snapshot of the current weights (on the buffer device)."""
        with self._lock.reading():
            return self._params

    def get_numpy(self):
        """Host copy (for HTTP/socket transports)."""
        with self._lock.reading():
            params = self._params
        return jax.device_get(params)

    def apply_delta(self, delta) -> None:
        """``weights -= delta`` on-device (reference update convention)."""
        delta = jax.device_put(delta, self._device)
        with self._lock.writing():
            self._params = self._apply(self._params, delta)
        with self._version_guard:
            self._version += 1

    def set(self, params) -> None:
        with self._lock.writing():
            self._params = jax.device_put(params, self._device)
