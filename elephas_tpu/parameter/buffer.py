"""HBM-resident parameter buffer — the async/hogwild weight store.

This replaces the reference's Flask/socket parameter server *state*
(``elephas/parameter/server.py::HttpServer``'s weight list guarded by
``RWLock`` — SURVEY.md §2.1, §5.2): the canonical weights live as
``jax.Array``s on a designated device (HBM on TPU), and applying a delta
is a jitted on-device subtract — the weights never bounce through host
memory on the single-host path.

Locking discipline (SURVEY.md §2.2):
- ``lock=True``  (asynchronous): writer-preferring RWLock around
  pull/apply — Downpour SGD with consistent snapshots.
- ``lock=False`` (hogwild): ``NullLock``; pulls may interleave with
  applies. The CPython GIL still makes each pointer swap atomic, so
  "race" means stale/interleaved pytree reads — the Hogwild! contract,
  not corruption (the reference's memory-model difference, documented).

Hogwild memory model, quantified (``tests/test_hogwild_races.py``,
8-thread deliberate contention, jitted CPU apply):

- ``granularity='tree'`` (default): ``apply_delta`` is a whole-pytree
  read-modify-write, so a racing apply can drop an ENTIRE delta —
  coarser than Hogwild!'s per-coordinate races. Measured applied
  fraction ≈0.3–0.9 across runs (mean ≈0.6, i.e. ~40% of deltas lost).
- ``granularity='leaf'``: every leaf lives in its own dict slot
  (GIL-atomic assignment), so a race drops at most the overlapping
  leaves — the closest analogue of the reference's in-place per-element
  mutation. Measured applied fraction **≈0.80, stable across runs**, at
  the cost of one dispatch per leaf per apply.

Values are never torn in either mode — survivors are exact sums of
whole per-leaf deltas — and ``version`` counts attempts, so the loss
rate is observable as ``1 - applied/version``. Training converges
either way (dropped deltas are unbiased); use ``lock=True`` when every
update must land.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

from elephas_tpu.utils import locksan
from elephas_tpu.utils.functional_utils import subtract_params
from elephas_tpu.utils.rwlock import NullLock, RWLock


class ParameterBuffer:
    """``granularity`` (hogwild only): ``'tree'`` applies a delta as one
    whole-pytree read-modify-write — a racing apply can drop an ENTIRE
    delta (mean ≈40% of deltas lost under 8-thread contention, see the
    module note). ``'leaf'`` applies leaf-by-leaf against per-leaf
    storage slots, so a race drops at most the single leaves it overlaps
    on — the closest GIL-level analogue of Hogwild!'s per-coordinate
    races (the reference mutates one shared weight list in place);
    measured applied fraction ≈0.80, stable
    (``tests/test_hogwild_races.py``). With ``lock=True`` the two are
    equivalent (the write lock serializes either way); 'tree' is the
    default for its lower per-apply overhead."""

    def __init__(self, params, lock: bool = True, device: Optional[jax.Device] = None,
                 granularity: str = "tree"):
        if granularity not in ("tree", "leaf"):
            raise ValueError(f"granularity must be tree|leaf, got {granularity!r}")
        self._device = device if device is not None else jax.devices()[0]
        self._lock = RWLock(name="ParameterBuffer._lock") if lock else NullLock()
        self._apply = jax.jit(subtract_params)
        self._apply_leaf = jax.jit(lambda a, b: a - b)
        self._granularity = granularity
        self._version = 0
        self._version_guard = locksan.make_lock("ParameterBuffer._version_guard")
        params = jax.device_put(params, self._device)
        if granularity == "leaf":
            # Per-leaf SLOTS: each leaf lives under its own dict key, and
            # a dict-item assignment is atomic under the GIL — so a racing
            # apply can clobber only the slots whose read-modify-write
            # windows it overlaps, never unrelated leaves. (A whole-tree
            # pointer swap per leaf would still lose OTHER leaves'
            # concurrent updates and is strictly worse than 'tree'.)
            # The (treedef, paths, store) triple is published as ONE
            # attribute so structure swaps in set() stay GIL-atomic for
            # lock-free readers.
            self._leaf_state = self._build_leaf_state(params)
            self._params = None
        else:
            self._params = params

    @staticmethod
    def _build_leaf_state(params):
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        return (treedef, [p for p, _ in flat], {p: a for p, a in flat})

    @property
    def device(self) -> jax.Device:
        return self._device

    @property
    def version(self) -> int:
        """Number of ATTEMPTED updates (staleness tests / diagnostics).

        Under ``lock=False`` attempts can overwrite each other, so the
        applied count can be lower — see the module docstring's
        lost-update note."""
        return self._version

    def get(self):
        """Snapshot of the current weights (on the buffer device)."""
        with self._lock.reading():
            if self._granularity == "leaf":
                treedef, paths, store = self._leaf_state
                return jax.tree_util.tree_unflatten(
                    treedef, [store[p] for p in paths]
                )
            return self._params

    def get_with_version(self):
        """``(version, snapshot)`` for the servers' version-gated cache.

        The version is read BEFORE the snapshot (both under the read
        lock; the ordering matters for ``lock=False``/hogwild where the
        read lock is a no-op): a racing ``apply_delta`` can only make
        the snapshot NEWER than the reported version, so a cache keyed
        on it re-encodes at worst — it can never hand out a stale
        not-modified reply for content the client hasn't seen."""
        with self._lock.reading():
            version = self._version
            if self._granularity == "leaf":
                treedef, paths, store = self._leaf_state
                snap = jax.tree_util.tree_unflatten(
                    treedef, [store[p] for p in paths]
                )
            else:
                snap = self._params
        return version, snap

    def get_numpy(self):
        """Host copy (for HTTP/socket transports)."""
        return jax.device_get(self.get())

    def get_numpy_with_version(self):
        """``(version, host-copy snapshot)``; device fetch happens AFTER
        the read lock is released (see ``get_with_version``)."""
        version, snap = self.get_with_version()
        return version, jax.device_get(snap)

    def apply_delta(self, delta) -> None:
        """``weights -= delta`` on-device (reference update convention)."""
        delta = jax.device_put(delta, self._device)
        with self._lock.writing():
            if self._granularity == "tree":
                self._params = self._apply(self._params, delta)
            else:
                self._apply_per_leaf(delta)
            # Version must move INSIDE the write lock: bumping after
            # release would let a reader observe the new content under
            # the old version and cache it — every later pull at the
            # real version would then get a stale "not modified".
            with self._version_guard:
                self._version += 1

    def _apply_per_leaf(self, delta) -> None:
        """One read-modify-write per leaf SLOT: under NullLock a
        concurrent apply can clobber only the slots whose windows it
        overlaps — unrelated leaves always land."""
        _, _, store = self._leaf_state
        flat_delta, _ = jax.tree_util.tree_flatten_with_path(delta)
        for path, leaf_delta in flat_delta:
            store[path] = self._apply_leaf(store[path], leaf_delta)

    def set(self, params, version: Optional[int] = None) -> None:
        """Replace the stored tree. ``version`` (warm restart only):
        resume the counter at a WAL snapshot's durable version instead
        of bumping — the restarted server's version line continues where
        the durable history left off. Stale-cache safety does NOT rest
        on this number: version-gated pulls are additionally keyed on
        the server's per-process boot id (``parameter/server.py``)."""
        with self._lock.writing():
            params = jax.device_put(params, self._device)
            if self._granularity == "leaf":
                # Built off to the side, published with one assignment:
                # lock-free readers see either the old or the new state,
                # never a mixed treedef/paths/store.
                self._leaf_state = self._build_leaf_state(params)
            else:
                self._params = params
            # set() replaces content, so it must invalidate
            # version-keyed snapshot caches exactly like apply_delta.
            with self._version_guard:
                if version is not None:
                    self._version = int(version)
                else:
                    self._version += 1
