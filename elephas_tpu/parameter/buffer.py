"""HBM-resident parameter buffer — the async/hogwild weight store.

This replaces the reference's Flask/socket parameter server *state*
(``elephas/parameter/server.py::HttpServer``'s weight list guarded by
``RWLock`` — SURVEY.md §2.1, §5.2): the canonical weights live as
``jax.Array``s on a designated device (HBM on TPU), and applying a delta
is a jitted on-device subtract — the weights never bounce through host
memory on the single-host path.

Locking discipline (SURVEY.md §2.2):
- ``lock=True``  (asynchronous): writer-preferring RWLock around
  pull/apply — Downpour SGD with consistent snapshots.
- ``lock=False`` (hogwild): ``NullLock``; pulls may interleave with
  applies. The CPython GIL still makes each pointer swap atomic, so
  "race" means stale/interleaved pytree reads — the Hogwild! contract,
  not corruption (the reference's memory-model difference, documented).
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

from elephas_tpu.utils.functional_utils import subtract_params
from elephas_tpu.utils.rwlock import NullLock, RWLock


class ParameterBuffer:
    def __init__(self, params, lock: bool = True, device: Optional[jax.Device] = None):
        self._device = device if device is not None else jax.devices()[0]
        self._params = jax.device_put(params, self._device)
        self._lock = RWLock() if lock else NullLock()
        self._apply = jax.jit(subtract_params)
        self._version = 0
        self._version_guard = threading.Lock()

    @property
    def device(self) -> jax.Device:
        return self._device

    @property
    def version(self) -> int:
        """Number of applied updates (staleness tests / diagnostics)."""
        return self._version

    def get(self):
        """Snapshot of the current weights (on the buffer device)."""
        with self._lock.reading():
            return self._params

    def get_numpy(self):
        """Host copy (for HTTP/socket transports)."""
        with self._lock.reading():
            params = self._params
        return jax.device_get(params)

    def apply_delta(self, delta) -> None:
        """``weights -= delta`` on-device (reference update convention)."""
        delta = jax.device_put(delta, self._device)
        with self._lock.writing():
            self._params = self._apply(self._params, delta)
        with self._version_guard:
            self._version += 1

    def set(self, params) -> None:
        with self._lock.writing():
            self._params = jax.device_put(params, self._device)
