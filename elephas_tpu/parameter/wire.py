"""Packed zero-copy wire codec for parameter-server payloads.

The reference ships every ``GET /parameters`` / ``POST /update`` as a
pickled weight list (SURVEY.md §2.1) and our port kept that cost: a
pull re-pickled the whole nested numpy tree per request (one full copy
serverside), and a push unpickled into fresh allocations. This module
replaces pickle on the PS hot path with a *packed* frame:

    [magic "EPK1"][u32 header_len][header JSON][pad][payload region]

- The header is small JSON metadata: a structure *skeleton* (dict keys /
  list arity, with leaves as indices), and per-leaf ``(dtype, shape,
  offset, nbytes, qdtype, scale)`` rows pointing into ONE contiguous
  payload region.
- **Encode is zero-copy**: each contiguous leaf is emitted as a
  ``memoryview`` of its own buffer (``Frames.chunks``) — the socket
  layer writes the chunks out without ever concatenating
  header+MAC+payload into a throwaway ``bytes``.
- **Decode is zero-copy**: leaves come back as ``np.frombuffer`` views
  into the received frame, so a 46 MB pull costs zero deserialization
  copies (the views are read-only; ``jax.device_put`` copies them onto
  the chip as it would any host array).
- Optional **delta quantization** (``quantize='bf16'|'f16'``) halves
  push bytes: float leaves are cast per-leaf (f16 with a per-leaf scale
  so large deltas don't overflow the ±65504 range; bf16 keeps f32's
  exponent so scale stays 1). Decode restores the original dtype.
  Quantization is lossy — see README's convergence caveat; the
  unquantized path is bit-exact.
- **Magic-byte negotiation**: frames are self-describing. ``is_packed``
  sniffs the 4-byte magic, so every receive path accepts packed AND
  legacy pickle bytes (pickle protocol ≥2 starts with ``b"\\x80"``,
  which can never collide with the ASCII magics) — legacy pickle peers
  keep working against the new servers.
- A **not-modified** frame (magic ``EPNM`` + u64 version, 12 bytes)
  answers a pull whose client already holds the current
  ``ParameterBuffer.version`` — O(header) on the wire instead of
  O(model).

This module is also the ONLY sanctioned home of ``pickle`` in
``elephas_tpu/parameter/`` (``encode_pickle``/``decode_pickle``);
``scripts/lint_blocking.py`` rejects direct pickle calls elsewhere in
the package so the hot path can't silently regress.
"""

from __future__ import annotations

import json
import pickle
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elephas_tpu.utils.sockets import (MAGIC_KV, MAGIC_NOTMOD, MAGIC_REJECT,
                                       MAGIC_TREE, RawPayload)

__all__ = [
    "DecodedTree",
    "DeltaRejected",
    "Frames",
    "NotModified",
    "WireFormatError",
    "decode",
    "decode_kv_blocks",
    "decode_payload",
    "decode_payload_traced",
    "decode_pickle",
    "decode_push",
    "encode_kv_blocks",
    "encode_not_modified",
    "encode_pickle",
    "encode_rejected",
    "encode_tree",
    "is_packed",
]

_HLEN = struct.Struct("!I")
_U64 = struct.Struct("!Q")
_ALIGN = 64  # leaf offsets are 64B-aligned so frombuffer views vectorize
_PREFIX = len(MAGIC_TREE) + _HLEN.size

# f16 quantization headroom: per-leaf scale maps max|x| to this, safely
# inside float16's ±65504 so the cast never overflows to inf.
_F16_HEADROOM = 6.0e4


class WireFormatError(ValueError):
    """Malformed, truncated, or structurally unsupported wire frame."""


class Frames(RawPayload):
    """An encoded frame as scatter-gather chunks (no concatenation).

    ``chunks`` is a list of buffer-protocol objects (the header bytes,
    per-leaf alignment pads, and zero-copy leaf memoryviews);
    ``nbytes`` is their total. The socket layer sends chunks directly
    (``utils.sockets.send``), the HTTP server writes them sequentially
    after Content-Length; ``tobytes()`` is for callers that need one
    buffer (HTTP client request bodies, tests).
    """

    __slots__ = ()

    def tobytes(self) -> bytes:
        return b"".join(bytes(c) for c in self.chunks)


class NotModified:
    """Decoded ``EPNM`` frame: the server's tree is unchanged at ``version``."""

    __slots__ = ("version",)

    def __init__(self, version: int):
        self.version = version

    def __repr__(self):
        return f"NotModified(version={self.version})"


class DeltaRejected:
    """Decoded ``EPRJ`` frame: the server refused to apply a pushed
    delta because it was staler than the admission policy's hard bound.

    ``version`` is the server's live buffer version at rejection time —
    the client should re-pull before retraining, and the frame carries
    the target so a worker can tell how far behind it fell. ``lag`` is
    the measured staleness (live version minus the push's
    ``seen_version``) and ``max_staleness`` the bound it crossed, so the
    surfaced exception's message is self-diagnosing."""

    __slots__ = ("version", "lag", "max_staleness")

    def __init__(self, version: int, lag: int, max_staleness: int):
        self.version = version
        self.lag = lag
        self.max_staleness = max_staleness

    def __repr__(self):
        return (f"DeltaRejected(version={self.version}, lag={self.lag}, "
                f"max_staleness={self.max_staleness})")


class DecodedTree:
    """Decoded ``EPK1`` frame: ``tree`` (zero-copy leaves) + ``version``
    (+ the serving server's ``boot`` id, when it sent one).

    ``boot`` (resilience layer): a parameter server mints a fresh random
    boot id per process start, and version-gated pulls must match
    (boot, version) — a server warm-restarted from a WAL snapshot resumes
    an OLD version counter, so version alone could collide with a
    client's cache and yield a stale not-modified.

    ``trace`` (observability layer): the sender's active
    ``(trace_id, span_id)`` pair, when it shipped one — the PS handler
    adopts it so its handle span joins the client's causal tree.

    ``seen_version``/``worker`` (training-health layer): on a *push*
    frame, the buffer version the worker trained its delta against and
    the worker's stable id — the PS's staleness accounting subtracts
    ``seen_version`` from its live version at apply time. Optional,
    absent from the header JSON when the sender didn't stamp them.

    ``sync_interval`` (admission layer): the pusher's current adaptive
    units-per-push, self-reported so the PS ledger (and the fleet SYNC
    column) can show each worker's effective sync cadence. Same
    omitted-when-None contract."""

    __slots__ = ("tree", "version", "boot", "trace", "seen_version",
                 "worker", "sync_interval")

    def __init__(self, tree, version: Optional[int], boot: Optional[str] = None,
                 trace: Optional[Tuple[str, str]] = None,
                 seen_version: Optional[int] = None,
                 worker: Optional[str] = None,
                 sync_interval: Optional[float] = None):
        self.tree = tree
        self.version = version
        self.boot = boot
        self.trace = trace
        self.seen_version = seen_version
        self.worker = worker
        self.sync_interval = sync_interval


def is_packed(buf) -> bool:
    """True iff ``buf`` starts with a packed-codec magic."""
    head = bytes(memoryview(buf)[:4])
    return (head == MAGIC_TREE or head == MAGIC_NOTMOD
            or head == MAGIC_REJECT or head == MAGIC_KV)


# -- structure skeleton -------------------------------------------------------
#
# The skeleton mirrors the pytree's container structure in JSON with
# leaves replaced by payload indices:  ["d", [[key, sub], ...]] for
# dicts, ["l"/"t", [sub, ...]] for lists/tuples, ["z"] for None, and
# ["f", i] for leaf i. Unlike path lists it round-trips EMPTY subtrees
# (``{"a": {}}``) exactly. Containers outside dict/list/tuple (custom
# pytree nodes) raise WireFormatError — callers fall back to pickle.


def _build_skeleton(obj, leaves: List[Any]):
    if obj is None:
        return ["z"]
    if isinstance(obj, dict):
        items = []
        for key, val in obj.items():
            if not isinstance(key, (str, int, float, bool)):
                raise WireFormatError(
                    f"packed codec needs JSON-able dict keys, got {type(key)}"
                )
            items.append([key, _build_skeleton(val, leaves)])
        return ["d", items]
    if isinstance(obj, (list, tuple)):
        tag = "l" if isinstance(obj, list) else "t"
        return [tag, [_build_skeleton(v, leaves) for v in obj]]
    idx = len(leaves)
    leaves.append(obj)
    return ["f", idx]


def _restore_skeleton(skel, leaves: List[Any]):
    tag = skel[0]
    if tag == "z":
        return None
    if tag == "f":
        return leaves[skel[1]]
    if tag == "d":
        return {key: _restore_skeleton(sub, leaves) for key, sub in skel[1]}
    if tag == "l":
        return [_restore_skeleton(sub, leaves) for sub in skel[1]]
    if tag == "t":
        return tuple(_restore_skeleton(sub, leaves) for sub in skel[1])
    raise WireFormatError(f"unknown skeleton tag {tag!r}")


# -- dtypes -------------------------------------------------------------------


def _np_dtype(name: str) -> np.dtype:
    """dtype by name, reaching into ml_dtypes for bf16 & friends."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError):
        raise WireFormatError(f"unknown wire dtype {name!r}") from None


def _quantize_leaf(arr: np.ndarray, quantize: str) -> Tuple[np.ndarray, str, float]:
    """(quantized array, qdtype name, scale). Raises on unknown mode."""
    if quantize == "bf16":
        import ml_dtypes

        return arr.astype(ml_dtypes.bfloat16), "bfloat16", 1.0
    if quantize == "f16":
        amax = float(np.max(np.abs(arr))) if arr.size else 0.0
        if not np.isfinite(amax) or amax == 0.0:
            scale = 1.0
        else:
            scale = amax / _F16_HEADROOM
        return (arr / scale).astype(np.float16), "float16", scale
    raise WireFormatError(f"quantize must be 'bf16'|'f16', got {quantize!r}")


def _leaf_chunk(arr: np.ndarray):
    """A zero-copy byte view of a contiguous array (copy only if the
    buffer protocol refuses the dtype — e.g. some extension dtypes)."""
    try:
        return memoryview(arr).cast("B")
    except (TypeError, ValueError, BufferError):
        return arr.tobytes()


# -- encode -------------------------------------------------------------------


def encode_tree(tree, version: Optional[int] = None,
                quantize: Optional[str] = None,
                boot: Optional[str] = None,
                trace: Optional[Tuple[str, str]] = None,
                seen_version: Optional[int] = None,
                worker: Optional[str] = None,
                sync_interval: Optional[float] = None) -> Frames:
    """Encode a pytree of arrays/scalars into a packed frame.

    ``boot``: the serving PS's boot id, carried in the header so clients
    can key their pull cache on (boot, version) — omitted (and absent
    from the JSON) when None, keeping frames byte-identical with
    pre-resilience peers. Raises ``WireFormatError`` for structures the
    skeleton can't carry (non-JSON dict keys, custom container nodes) —
    callers fall back to ``encode_pickle``.

    ``trace``: the sender's active ``(trace_id, span_id)`` — carried as
    ``"tc"`` in the header so the receiving PS's handle span joins the
    sender's trace. Like ``boot``, omitted entirely when None: frames
    from untraced processes stay byte-identical with older peers.

    ``seen_version``/``worker``: push-side staleness stamps, carried as
    ``"sv"``/``"wk"`` under the same omitted-when-None contract — the PS
    measures version lag only on frames that declare what they trained
    against, and legacy frames stay byte-identical.

    ``sync_interval``: the pusher's adaptive units-per-push, carried as
    ``"si"`` under the same contract — pure telemetry for the PS
    ledger's SYNC column, never part of the admission decision.
    """
    leaves: List[Any] = []
    skeleton = _build_skeleton(tree, leaves)

    rows = []          # (dtype, shape, offset, nbytes, qdtype, scale)
    payload_chunks = []  # alternating pads + leaf views
    offset = 0
    for leaf in leaves:
        arr = np.ascontiguousarray(leaf)
        if arr.dtype == object:
            raise WireFormatError("object-dtype leaf has no wire layout")
        qdtype, scale = None, None
        if quantize is not None and arr.dtype.kind == "f" \
                and arr.dtype.itemsize > 2:
            arr, qdtype, scale = _quantize_leaf(arr, quantize)
        pad = (-offset) % _ALIGN
        if pad:
            payload_chunks.append(b"\x00" * pad)
            offset += pad
        rows.append([np.asarray(leaf).dtype.name, list(np.shape(leaf)),
                     offset, arr.nbytes, qdtype, scale])
        payload_chunks.append(_leaf_chunk(arr))
        offset += arr.nbytes

    meta: Dict[str, Any] = {"v": 1, "ver": version, "skel": skeleton,
                            "leaves": rows}
    if boot is not None:
        meta["boot"] = str(boot)
    if trace is not None:
        meta["tc"] = [str(trace[0]), str(trace[1])]
    if seen_version is not None:
        meta["sv"] = int(seen_version)
    if worker is not None:
        meta["wk"] = str(worker)
    if sync_interval is not None:
        meta["si"] = float(sync_interval)
    header = json.dumps(meta, separators=(",", ":")).encode()
    # Pad the header with spaces (JSON-transparent) so the payload
    # region starts 64B-aligned relative to the frame start.
    header += b" " * ((-(_PREFIX + len(header))) % _ALIGN)
    head = MAGIC_TREE + _HLEN.pack(len(header)) + header
    return Frames([head, *payload_chunks])


def encode_not_modified(version: int) -> Frames:
    """The 12-byte "your snapshot is current" reply frame."""
    return Frames([MAGIC_NOTMOD + _U64.pack(int(version))])


def encode_rejected(version: int, lag: int, max_staleness: int) -> Frames:
    """The 28-byte "delta too stale, re-pull" push reply frame.

    Only emitted to peers that *stamped* their push (packed frames with
    ``sv``, or pickle bodies under staleness headers) — an unstamped
    legacy peer never sees this magic, preserving its old contract."""
    return Frames([MAGIC_REJECT + _U64.pack(int(version))
                   + _U64.pack(int(lag)) + _U64.pack(int(max_staleness))])


def encode_pickle(obj) -> bytes:
    """Legacy pickle codec — the package's ONLY sanctioned pickle.dumps."""
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def decode_pickle(buf):
    """Legacy pickle codec — the package's ONLY sanctioned pickle.loads.

    Callers MUST have authenticated ``buf`` first when a wire auth key
    is configured (``utils.sockets`` verifies HMAC before any payload
    reaches this)."""
    return pickle.loads(bytes(buf) if isinstance(buf, memoryview) else buf)


# -- decode -------------------------------------------------------------------


def decode(buf, expect_treedef=None):
    """Decode one packed frame → ``DecodedTree`` | ``NotModified``.

    Leaves are read-only ``np.frombuffer`` views into ``buf`` (keep it
    alive as long as the tree). ``expect_treedef`` (a
    ``jax.tree_util`` treedef) turns a structure mismatch into a
    ``WireFormatError`` instead of a downstream apply error.
    """
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.format != "B":
        mv = mv.cast("B")
    head = bytes(mv[:4])
    if head == MAGIC_NOTMOD:
        if len(mv) < 4 + _U64.size:
            raise WireFormatError("truncated not-modified frame")
        return NotModified(_U64.unpack_from(mv, 4)[0])
    if head == MAGIC_REJECT:
        if len(mv) < 4 + 3 * _U64.size:
            raise WireFormatError("truncated delta-rejected frame")
        return DeltaRejected(_U64.unpack_from(mv, 4)[0],
                             _U64.unpack_from(mv, 4 + _U64.size)[0],
                             _U64.unpack_from(mv, 4 + 2 * _U64.size)[0])
    if head != MAGIC_TREE:
        raise WireFormatError(
            f"not a packed frame (magic {head!r}; legacy pickle bodies "
            "go through decode_payload/decode_pickle)"
        )
    if len(mv) < _PREFIX:
        raise WireFormatError("truncated packed frame header")
    (hlen,) = _HLEN.unpack_from(mv, 4)
    if _PREFIX + hlen > len(mv):
        raise WireFormatError("packed frame shorter than its header length")
    try:
        header = json.loads(bytes(mv[_PREFIX:_PREFIX + hlen]))
    except ValueError as exc:
        raise WireFormatError(f"corrupt packed frame header: {exc}") from exc
    if header.get("v") != 1:
        raise WireFormatError(f"unsupported packed frame version {header.get('v')!r}")

    payload = mv[_PREFIX + hlen:]
    leaves = []
    for dtype_name, shape, offset, nbytes, qdtype, scale in header["leaves"]:
        if offset + nbytes > len(payload):
            raise WireFormatError(
                f"leaf at offset {offset} (+{nbytes}B) overruns the "
                f"{len(payload)}B payload region (truncated frame?)"
            )
        wire_dtype = _np_dtype(qdtype or dtype_name)
        arr = np.frombuffer(payload, dtype=wire_dtype,
                            count=nbytes // wire_dtype.itemsize,
                            offset=offset).reshape(shape)
        if qdtype is not None:
            out_dtype = _np_dtype(dtype_name)
            arr = arr.astype(out_dtype)
            if scale != 1.0:
                arr = arr * out_dtype.type(scale)
        leaves.append(arr)
    tree = _restore_skeleton(header["skel"], leaves)
    if expect_treedef is not None:
        import jax

        got = jax.tree_util.tree_structure(tree)
        if got != expect_treedef:
            raise WireFormatError(
                f"packed frame treedef mismatch: got {got}, expected "
                f"{expect_treedef}"
            )
    tc = header.get("tc")
    return DecodedTree(tree, header.get("ver"), header.get("boot"),
                       tuple(tc) if tc else None,
                       header.get("sv"), header.get("wk"),
                       header.get("si"))


def decode_payload(buf, expect_treedef=None):
    """Decode a request/response body of EITHER codec into a tree.

    Magic-byte negotiation: packed frames are self-describing, anything
    else is legacy pickle — so one receive path serves new packed peers
    and old pickle peers alike. A ``NotModified`` frame is invalid here
    (it only answers version-gated pulls).
    """
    if is_packed(buf):
        out = decode(buf, expect_treedef=expect_treedef)
        if isinstance(out, (NotModified, DeltaRejected)):
            raise WireFormatError(
                f"status frame {out!r} where a tree was expected")
        return out.tree
    return decode_pickle(buf)


def decode_payload_traced(buf, expect_treedef=None):
    """``decode_payload`` that also surfaces the sender's trace context:
    ``(tree, (trace_id, span_id) | None)``. The PS push handlers use
    this so ``buffer.apply_delta`` runs under the pushing worker's
    trace; legacy pickle bodies carry no context (the pickle *frame*
    does, upstream, via the 3-tuple socket shape)."""
    if is_packed(buf):
        out = decode(buf, expect_treedef=expect_treedef)
        if isinstance(out, (NotModified, DeltaRejected)):
            raise WireFormatError(
                f"status frame {out!r} where a tree was expected")
        return out.tree, out.trace
    return decode_pickle(buf), None


# -- KV-block handoff frames --------------------------------------------------
#
# The disaggregated-serving payload kind: a prefill replica ships one
# request's filled KV blocks (plus the block-table/prefix-chain metadata
# the decode side needs to rebind them) as
#
#     [magic "EPKV"][u32 header_len][header JSON][pad][payload region]
#
# — the same layout discipline as EPK1 frames (64B-aligned leaf offsets,
# zero-copy encode via memoryview chunks, zero-copy decode via
# np.frombuffer views), but with a free-form JSON ``meta`` dict instead
# of a pytree skeleton: the serving layer owns the metadata schema
# (tokens, block size, chain keys), the codec owns only bytes.


def encode_kv_blocks(meta: Dict[str, Any], arrays: List[np.ndarray]) -> Frames:
    """Encode a KV handoff: JSON-able ``meta`` + a list of block arrays.

    Each array lands contiguously at a 64B-aligned offset in one payload
    region; the header carries ``(dtype, shape, offset, nbytes)`` rows in
    list order so ``decode_kv_blocks`` restores them positionally.
    Raises ``WireFormatError`` for non-JSON meta or object-dtype arrays.
    """
    rows = []
    payload_chunks: List[Any] = []
    offset = 0
    for leaf in arrays:
        arr = np.ascontiguousarray(leaf)
        if arr.dtype == object:
            raise WireFormatError("object-dtype leaf has no wire layout")
        pad = (-offset) % _ALIGN
        if pad:
            payload_chunks.append(b"\x00" * pad)
            offset += pad
        rows.append([arr.dtype.name, list(arr.shape), offset, arr.nbytes])
        payload_chunks.append(_leaf_chunk(arr))
        offset += arr.nbytes
    try:
        header = json.dumps({"v": 1, "meta": meta, "leaves": rows},
                            separators=(",", ":")).encode()
    except (TypeError, ValueError) as exc:
        raise WireFormatError(
            f"KV handoff meta is not JSON-able: {exc}") from exc
    header += b" " * ((-(_PREFIX + len(header))) % _ALIGN)
    head = MAGIC_KV + _HLEN.pack(len(header)) + header
    return Frames([head, *payload_chunks])


def decode_kv_blocks(buf) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """Decode one ``EPKV`` frame → ``(meta, arrays)``.

    Arrays are read-only ``np.frombuffer`` views into ``buf`` (keep it
    alive as long as the arrays). Every structural defect — wrong magic,
    truncation, corrupt JSON, a leaf overrunning the payload — raises
    ``WireFormatError``; the serving layer's reject path maps that to a
    local re-prefill instead of a wedged slot.
    """
    mv = memoryview(buf)
    if mv.ndim != 1 or mv.format != "B":
        mv = mv.cast("B")
    if bytes(mv[:4]) != MAGIC_KV:
        raise WireFormatError(
            f"not a KV handoff frame (magic {bytes(mv[:4])!r})")
    if len(mv) < _PREFIX:
        raise WireFormatError("truncated KV handoff frame header")
    (hlen,) = _HLEN.unpack_from(mv, 4)
    if _PREFIX + hlen > len(mv):
        raise WireFormatError("KV handoff frame shorter than its header length")
    try:
        header = json.loads(bytes(mv[_PREFIX:_PREFIX + hlen]))
    except ValueError as exc:
        raise WireFormatError(f"corrupt KV handoff header: {exc}") from exc
    if header.get("v") != 1 or not isinstance(header.get("leaves"), list):
        raise WireFormatError(
            f"unsupported KV handoff frame version {header.get('v')!r}")
    payload = mv[_PREFIX + hlen:]
    arrays = []
    for row in header["leaves"]:
        try:
            dtype_name, shape, offset, nbytes = row
        except (TypeError, ValueError) as exc:
            raise WireFormatError(f"malformed KV leaf row {row!r}") from exc
        if offset + nbytes > len(payload):
            raise WireFormatError(
                f"KV leaf at offset {offset} (+{nbytes}B) overruns the "
                f"{len(payload)}B payload region (truncated frame?)"
            )
        dtype = _np_dtype(dtype_name)
        try:
            arr = np.frombuffer(payload, dtype=dtype,
                                count=nbytes // dtype.itemsize,
                                offset=offset).reshape(shape)
        except (TypeError, ValueError) as exc:
            raise WireFormatError(
                f"KV leaf {row!r} does not reshape: {exc}") from exc
        arrays.append(arr)
    meta = header.get("meta")
    if not isinstance(meta, dict):
        raise WireFormatError("KV handoff frame carries no meta dict")
    return meta, arrays


def decode_push(buf, expect_treedef=None):
    """``decode_payload`` for the PS push handlers: surfaces the sender's
    trace context AND staleness stamps as ``(tree, trace, seen_version,
    worker, sync_interval)``. Legacy pickle bodies decode with every
    stamp ``None`` — staleness simply isn't measured for peers that
    don't declare it."""
    if is_packed(buf):
        out = decode(buf, expect_treedef=expect_treedef)
        if isinstance(out, (NotModified, DeltaRejected)):
            raise WireFormatError(
                f"status frame {out!r} where a tree was expected")
        return (out.tree, out.trace, out.seen_version, out.worker,
                out.sync_interval)
    return decode_pickle(buf), None, None, None, None
