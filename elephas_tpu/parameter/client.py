"""Parameter clients (reference: ``elephas/parameter/client.py``).

``HttpClient``/``SocketClient`` keep the reference's wire behavior
(SURVEY.md §2.1 "PS clients"); ``LocalClient`` is the TPU-native
in-process fast path — a pull is a device-to-device copy out of the HBM
buffer, a push is a jitted on-device subtract.
"""

from __future__ import annotations

import pickle
import socket
import threading
import urllib.request

import jax

from elephas_tpu.parameter.base import BaseParameterClient
from elephas_tpu.parameter.buffer import ParameterBuffer
from elephas_tpu.utils import sockets as socket_utils


class LocalClient(BaseParameterClient):
    def __init__(self, buffer: ParameterBuffer):
        self._buffer = buffer

    def get_parameters(self):
        return self._buffer.get()

    def update_parameters(self, delta) -> None:
        self._buffer.apply_delta(delta)


class HttpClient(BaseParameterClient):
    """urllib against ``GET /parameters`` / ``POST /update``."""

    def __init__(self, master_url: str, timeout: float = 60.0):
        self.master_url = master_url
        self.timeout = timeout

    def get_parameters(self):
        with urllib.request.urlopen(
            f"http://{self.master_url}/parameters", timeout=self.timeout
        ) as resp:
            return pickle.loads(resp.read())

    def update_parameters(self, delta) -> None:
        delta = jax.device_get(delta)
        payload = pickle.dumps(delta, protocol=pickle.HIGHEST_PROTOCOL)
        req = urllib.request.Request(
            f"http://{self.master_url}/update",
            data=payload,
            headers={"Content-Type": "application/octet-stream"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout):
            pass


class SocketClient(BaseParameterClient):
    """Persistent framed-TCP connection (one per worker thread)."""

    def __init__(self, master_url: str):
        host, port = master_url.rsplit(":", 1)
        self._addr = (host, int(port))
        self._sock = None
        self._lock = threading.Lock()  # one in-flight request per connection

    def _connection(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self._addr, timeout=60.0)
        return self._sock

    def get_parameters(self):
        with self._lock:
            sock = self._connection()
            socket_utils.send(sock, ("g", None))
            return socket_utils.receive(sock)

    def update_parameters(self, delta) -> None:
        delta = jax.device_get(delta)
        with self._lock:
            sock = self._connection()
            socket_utils.send(sock, ("u", delta))
            socket_utils.receive(sock)  # ack

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
