"""Parameter clients (reference: ``elephas/parameter/client.py``).

``HttpClient``/``SocketClient`` keep the reference's wire behavior
(SURVEY.md §2.1 "PS clients"); ``LocalClient`` is the TPU-native
in-process fast path — a pull is a device-to-device copy out of the HBM
buffer, a push is a jitted on-device subtract.

Failure model: the reference inherits Spark's task-retry safety net; we
have none (SURVEY.md §5.3), so the wire clients fail FAST instead of
hanging — connection-level failures are retried with exponential backoff
for a small budget (~3s), then raised as ``ParameterServerUnavailable``
naming the address, so a dead PS surfaces as an actionable error within
seconds rather than a 60s socket stall per call.
"""

from __future__ import annotations

import pickle
import socket
import threading
import time
import urllib.error
import urllib.request

import jax

from elephas_tpu.parameter.base import BaseParameterClient
from elephas_tpu.parameter.buffer import ParameterBuffer
from elephas_tpu.utils import sockets as socket_utils

# Connection-failure retry schedule: total sleep ~2.8s before giving up.
_RETRY_DELAYS = (0.1, 0.2, 0.4, 0.8, 1.3)
_CONNECT_TIMEOUT = 2.0  # dial budget per attempt (transfers get self.timeout)


class ParameterServerUnavailable(ConnectionError):
    """The parameter server could not be reached after retries."""


def _retry_connect(fn, address: str, op: str):
    """Run ``fn`` retrying connection-level failures with backoff.

    Anything that indicates the server is *gone* (refused, reset, DNS,
    dial timeout) is retried then converted to ParameterServerUnavailable;
    application-level errors (HTTP 4xx/5xx) propagate immediately.
    """
    last: Exception | None = None
    for delay in (*_RETRY_DELAYS, None):
        try:
            return fn()
        except urllib.error.HTTPError:
            raise  # server alive, request bad — not a connectivity issue
        except (ConnectionError, socket.timeout, TimeoutError, OSError, urllib.error.URLError) as exc:
            last = exc
        if delay is None:
            break
        time.sleep(delay)
    raise ParameterServerUnavailable(
        f"parameter server at {address} unreachable during {op} "
        f"(retried {len(_RETRY_DELAYS)}x over ~{sum(_RETRY_DELAYS):.1f}s): {last}"
    ) from last


class LocalClient(BaseParameterClient):
    def __init__(self, buffer: ParameterBuffer):
        self._buffer = buffer

    def get_parameters(self):
        return self._buffer.get()

    def update_parameters(self, delta) -> None:
        self._buffer.apply_delta(delta)

    def wait_barrier(self, tag: str, n: int, timeout: float = 600.0) -> None:
        pass  # in-process buffer == single host; nothing to synchronize


class _WireBarrierMixin:
    """PS-backed host barrier: arrive once, then poll the arrival count.

    Used for fit teardown across hosts. Polling the PS (instead of a
    device collective) tolerates arbitrary host drift — async workers can
    be minutes apart, far past collective-rendezvous deadlines.
    """

    def barrier_arrive(self, tag: str) -> int:
        raise NotImplementedError

    def barrier_count(self, tag: str) -> int:
        raise NotImplementedError

    def wait_barrier(self, tag: str, n: int, timeout: float = 600.0) -> None:
        self.barrier_arrive(tag)
        deadline = time.monotonic() + timeout
        poll = 0.02
        while time.monotonic() < deadline:
            if self.barrier_count(tag) >= n:
                return
            time.sleep(poll)
            poll = min(poll * 2, 0.5)
        raise TimeoutError(
            f"barrier {tag!r}: {self.barrier_count(tag)}/{n} hosts after {timeout}s"
        )


class HttpClient(_WireBarrierMixin, BaseParameterClient):
    """urllib against ``GET /parameters`` / ``POST /update``.

    ``timeout`` bounds the transfer once connected; dialing a dead/absent
    server fails within ``_CONNECT_TIMEOUT`` per attempt and is retried by
    ``_retry_connect`` (fail-fast, see module docstring).
    """

    def __init__(self, master_url: str, timeout: float = 60.0):
        self.master_url = master_url
        self.timeout = timeout

    def _url(self, path: str) -> str:
        return f"http://{self.master_url}{path}"

    def get_parameters(self):
        def attempt():
            with urllib.request.urlopen(
                self._url("/parameters"), timeout=self.timeout
            ) as resp:
                return pickle.loads(resp.read())

        return _retry_connect(attempt, self.master_url, "get_parameters")

    def update_parameters(self, delta) -> None:
        delta = jax.device_get(delta)
        payload = pickle.dumps(delta, protocol=pickle.HIGHEST_PROTOCOL)

        def attempt():
            req = urllib.request.Request(
                self._url("/update"),
                data=payload,
                headers={"Content-Type": "application/octet-stream"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass

        _retry_connect(attempt, self.master_url, "update_parameters")

    def health(self) -> bool:
        """One non-retried probe of ``GET /health`` (liveness check)."""
        try:
            with urllib.request.urlopen(
                self._url("/health"), timeout=_CONNECT_TIMEOUT
            ) as resp:
                return resp.status == 200
        except Exception:
            return False

    def barrier_arrive(self, tag: str) -> int:
        def attempt():
            req = urllib.request.Request(
                self._url(f"/barrier/{tag}"), data=b"", method="POST"
            )
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return int(resp.read())

        return _retry_connect(attempt, self.master_url, "barrier_arrive")

    def barrier_count(self, tag: str) -> int:
        def attempt():
            with urllib.request.urlopen(
                self._url(f"/barrier/{tag}"), timeout=self.timeout
            ) as resp:
                return int(resp.read())

        return _retry_connect(attempt, self.master_url, "barrier_count")


def make_client(mode: str, address: str) -> BaseParameterClient:
    """Client for a parameter server reachable at ``address`` ("ip:port").

    The cross-host worker path: hosts that did not start the server dial
    the address host 0 broadcast (reference topology — every worker talks
    to the one driver PS, SURVEY.md §3.2).
    """
    if mode == "http":
        return HttpClient(address)
    if mode == "socket":
        return SocketClient(address)
    raise ValueError(f"no wire client for parameter_server_mode={mode!r}")


class SocketClient(_WireBarrierMixin, BaseParameterClient):
    """Persistent framed-TCP connection (one per worker thread)."""

    def __init__(self, master_url: str, timeout: float = 60.0):
        host, port = master_url.rsplit(":", 1)
        self.master_url = master_url
        self._addr = (host, int(port))
        self.timeout = timeout
        self._sock = None
        self._lock = threading.Lock()  # one in-flight request per connection

    def _connection(self) -> socket.socket:
        if self._sock is None:
            def attempt():
                sock = socket.create_connection(self._addr, timeout=_CONNECT_TIMEOUT)
                sock.settimeout(self.timeout)
                return sock

            self._sock = _retry_connect(attempt, self.master_url, "connect")
        return self._sock

    def _roundtrip(self, frame, op: str):
        """Send one frame, read one reply; a connection that died between
        calls (PS restart) gets ONE reconnect, then fails fast."""
        for retry in (True, False):
            sock = self._connection()
            try:
                socket_utils.send(sock, frame)
                return socket_utils.receive(sock)
            except (ConnectionError, socket.timeout, TimeoutError, OSError) as exc:
                self._sock = None
                try:
                    sock.close()
                except OSError:
                    pass
                if not retry:
                    raise ParameterServerUnavailable(
                        f"parameter server at {self.master_url} dropped the "
                        f"connection during {op}: {exc}"
                    ) from exc

    def get_parameters(self):
        with self._lock:
            return self._roundtrip(("g", None), "get_parameters")

    def update_parameters(self, delta) -> None:
        delta = jax.device_get(delta)
        with self._lock:
            self._roundtrip(("u", delta), "update_parameters")

    def health(self) -> bool:
        """Liveness probe: a barrier *count* is read-only and cheap."""
        try:
            with self._lock:
                self._roundtrip(("c", "health"), "health")
            return True
        except Exception:
            return False

    def barrier_arrive(self, tag: str) -> int:
        with self._lock:
            return self._roundtrip(("b", tag), "barrier_arrive")

    def barrier_count(self, tag: str) -> int:
        with self._lock:
            return self._roundtrip(("c", tag), "barrier_count")

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
