"""Parameter clients (reference: ``elephas/parameter/client.py``).

``HttpClient``/``SocketClient`` keep the reference's wire behavior
(SURVEY.md §2.1 "PS clients"); ``LocalClient`` is the TPU-native
in-process fast path — a pull is a device-to-device copy out of the HBM
buffer, a push is a jitted on-device subtract.
"""

from __future__ import annotations

import pickle
import socket
import threading
import time
import urllib.request

import jax

from elephas_tpu.parameter.base import BaseParameterClient
from elephas_tpu.parameter.buffer import ParameterBuffer
from elephas_tpu.utils import sockets as socket_utils


class LocalClient(BaseParameterClient):
    def __init__(self, buffer: ParameterBuffer):
        self._buffer = buffer

    def get_parameters(self):
        return self._buffer.get()

    def update_parameters(self, delta) -> None:
        self._buffer.apply_delta(delta)

    def wait_barrier(self, tag: str, n: int, timeout: float = 600.0) -> None:
        pass  # in-process buffer == single host; nothing to synchronize


class _WireBarrierMixin:
    """PS-backed host barrier: arrive once, then poll the arrival count.

    Used for fit teardown across hosts. Polling the PS (instead of a
    device collective) tolerates arbitrary host drift — async workers can
    be minutes apart, far past collective-rendezvous deadlines.
    """

    def barrier_arrive(self, tag: str) -> int:
        raise NotImplementedError

    def barrier_count(self, tag: str) -> int:
        raise NotImplementedError

    def wait_barrier(self, tag: str, n: int, timeout: float = 600.0) -> None:
        self.barrier_arrive(tag)
        deadline = time.monotonic() + timeout
        poll = 0.02
        while time.monotonic() < deadline:
            if self.barrier_count(tag) >= n:
                return
            time.sleep(poll)
            poll = min(poll * 2, 0.5)
        raise TimeoutError(
            f"barrier {tag!r}: {self.barrier_count(tag)}/{n} hosts after {timeout}s"
        )


class HttpClient(_WireBarrierMixin, BaseParameterClient):
    """urllib against ``GET /parameters`` / ``POST /update``."""

    def __init__(self, master_url: str, timeout: float = 60.0):
        self.master_url = master_url
        self.timeout = timeout

    def get_parameters(self):
        with urllib.request.urlopen(
            f"http://{self.master_url}/parameters", timeout=self.timeout
        ) as resp:
            return pickle.loads(resp.read())

    def update_parameters(self, delta) -> None:
        delta = jax.device_get(delta)
        payload = pickle.dumps(delta, protocol=pickle.HIGHEST_PROTOCOL)
        req = urllib.request.Request(
            f"http://{self.master_url}/update",
            data=payload,
            headers={"Content-Type": "application/octet-stream"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout):
            pass

    def barrier_arrive(self, tag: str) -> int:
        req = urllib.request.Request(
            f"http://{self.master_url}/barrier/{tag}", data=b"", method="POST"
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return int(resp.read())

    def barrier_count(self, tag: str) -> int:
        with urllib.request.urlopen(
            f"http://{self.master_url}/barrier/{tag}", timeout=self.timeout
        ) as resp:
            return int(resp.read())


def make_client(mode: str, address: str) -> BaseParameterClient:
    """Client for a parameter server reachable at ``address`` ("ip:port").

    The cross-host worker path: hosts that did not start the server dial
    the address host 0 broadcast (reference topology — every worker talks
    to the one driver PS, SURVEY.md §3.2).
    """
    if mode == "http":
        return HttpClient(address)
    if mode == "socket":
        return SocketClient(address)
    raise ValueError(f"no wire client for parameter_server_mode={mode!r}")


class SocketClient(_WireBarrierMixin, BaseParameterClient):
    """Persistent framed-TCP connection (one per worker thread)."""

    def __init__(self, master_url: str):
        host, port = master_url.rsplit(":", 1)
        self._addr = (host, int(port))
        self._sock = None
        self._lock = threading.Lock()  # one in-flight request per connection

    def _connection(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self._addr, timeout=60.0)
        return self._sock

    def get_parameters(self):
        with self._lock:
            sock = self._connection()
            socket_utils.send(sock, ("g", None))
            return socket_utils.receive(sock)

    def update_parameters(self, delta) -> None:
        delta = jax.device_get(delta)
        with self._lock:
            sock = self._connection()
            socket_utils.send(sock, ("u", delta))
            socket_utils.receive(sock)  # ack

    def barrier_arrive(self, tag: str) -> int:
        with self._lock:
            sock = self._connection()
            socket_utils.send(sock, ("b", tag))
            return socket_utils.receive(sock)

    def barrier_count(self, tag: str) -> int:
        with self._lock:
            sock = self._connection()
            socket_utils.send(sock, ("c", tag))
            return socket_utils.receive(sock)

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
