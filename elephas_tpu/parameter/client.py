"""Parameter clients (reference: ``elephas/parameter/client.py``).

``HttpClient``/``SocketClient`` keep the reference's wire behavior
(SURVEY.md §2.1 "PS clients"); ``LocalClient`` is the TPU-native
in-process fast path — a pull is a device-to-device copy out of the HBM
buffer, a push is a jitted on-device subtract.

Failure model: the reference inherits Spark's task-retry safety net; we
have none (SURVEY.md §5.3), so the wire clients fail FAST instead of
hanging — connection-level failures are retried with exponential backoff
for a small budget (~3s), then raised as ``ParameterServerUnavailable``
naming the address, so a dead PS surfaces as an actionable error within
seconds rather than a 60s socket stall per call.

Idempotency & wedge handling: only connection ESTABLISHMENT is retried
(plus, for socket reads, one transparent reconnect of a pooled
connection that died between calls). Once a request has left the
socket, failures raise immediately — a re-sent write would double-apply
a delta or double-count a teardown-barrier arrival (tearing the PS down
under a peer mid-pull), and retrying a read timeout on an established
connection would stall ``timeout``-per-attempt against a wedged server.
NOTE this no-resend guarantee is the WIRE layer's only: the engine's
task-retry layer above (``AsyncTrainer`` ``run_unit``) re-runs a failed
frequency-unit end to end, so delta application is at-least-once
job-wide — see the run_unit docstring for why that is sound for SGD.
"""

from __future__ import annotations

import hmac
import http.client
import json
import os
import socket
import threading
import time
import warnings
from typing import Optional

import jax

from elephas_tpu import obs
from elephas_tpu.parameter import wire
from elephas_tpu.parameter.base import BaseParameterClient
from elephas_tpu.parameter.buffer import ParameterBuffer
from elephas_tpu.utils import sockets as socket_utils

# Connection-failure retry schedule: total sleep ~2.8s before giving up.
_RETRY_DELAYS = (0.1, 0.2, 0.4, 0.8, 1.3)
_CONNECT_TIMEOUT = 2.0  # dial budget per attempt (transfers get self.timeout)


# Literal metric names per op: the transport is a LABEL, not part of
# the name (one series family Prometheus can sum/relabel), and the lint
# naming rule can see the literals.
_PS_OP_COUNTERS = {"pull": "ps_pull_total", "push": "ps_push_total"}


def _ps_span(op: str, transport: str):
    """Span + counter for one PS round-trip; every client's pull/push
    funnels through here so ``ps/pull``/``ps/push`` rows mean the same
    thing across local, http, and socket transports. The wire clients
    ``note()`` payload bytes + codec onto the span (None-guarded: a
    disabled tracer yields None) and read ``sp.context`` for the
    ``(trace_id, span_id)`` pair to ship on the wire."""
    obs.default_registry().counter(
        _PS_OP_COUNTERS[op], labelnames=("transport",)
    ).labels(transport=transport).inc()
    return obs.default_tracer().span(f"ps/{op}", transport=transport)


def _span_trace(sp):
    """The wire-shippable ``(trace_id, span_id)`` of a live ``_ps_span``,
    or None (disabled tracer, or no trace root active — untraced runs
    keep the legacy wire shapes byte-identical)."""
    return sp.context if sp else None


def _resolve_codec(codec: Optional[str]) -> str:
    """Wire codec for this client: explicit arg > ``$ELEPHAS_PS_CODEC`` >
    packed. ``'pickle'`` is the legacy-interop escape hatch — REQUIRED
    when a SocketClient dials a pre-packed-codec server (the old socket
    server closes the connection on unknown frame kinds; the HTTP
    transport degrades transparently because responses self-describe by
    magic, but pinning 'pickle' avoids shipping packed pushes it would
    reject)."""
    codec = codec or os.environ.get("ELEPHAS_PS_CODEC", "packed")
    if codec not in ("packed", "pickle"):
        raise ValueError(f"codec must be packed|pickle, got {codec!r}")
    return codec


def _encode_push(delta, codec: str, quantize: Optional[str],
                 seen_version: Optional[int] = None,
                 worker: Optional[str] = None,
                 sync_interval: Optional[float] = None):
    """``(payload, codec_used)`` for one push. Structures the packed
    skeleton can't carry (custom pytree nodes) fall back to pickle —
    the server accepts either on one endpoint. ``seen_version``/
    ``worker``/``sync_interval`` are the staleness stamps packed frames
    carry in-header (pickle fallbacks lose them; the HTTP transport
    re-adds them as request headers)."""
    if codec == "packed":
        try:
            return wire.encode_tree(delta, quantize=quantize,
                                    seen_version=seen_version,
                                    worker=worker,
                                    sync_interval=sync_interval), "packed"
        except wire.WireFormatError:
            pass
    return wire.encode_pickle(delta), "pickle"


class _PullCache:
    """Client side of the version-gated pull: remembers the last
    ``(boot, version, tree)`` a full-body reply carried, advertises the
    ``(boot, version)`` position on the next pull, and resolves a
    not-modified reply back to the cached tree. The boot id scopes the
    version to one server life — after a PS warm restart the version
    counter resumes an old line, so version alone could alias pre-crash
    content (``server._new_boot_id``). Thread-safe (the pipelined engine
    pulls from a comms thread)."""

    __slots__ = ("_lock", "_version", "_tree", "_boot")

    def __init__(self):
        self._lock = threading.Lock()
        self._version = None
        self._tree = None
        self._boot = None

    def known_version(self):
        with self._lock:
            return self._version if self._tree is not None else None

    def known(self):
        """``(boot, version)`` to advertise, or None. Only a reply that
        carried a boot id is advertised as a pair — against a pre-boot-id
        server the bare version keeps the legacy wire shape."""
        with self._lock:
            if self._tree is None or self._version is None:
                return None
            if self._boot is None:
                return self._version
            return (self._boot, self._version)

    def store(self, version, tree, boot=None):
        if version is None:
            return
        with self._lock:
            self._version, self._tree, self._boot = version, tree, boot

    def resolve(self, not_modified: "wire.NotModified"):
        with self._lock:
            version, tree = self._version, self._tree
        if tree is None or not_modified.version != version:
            obs.default_flight_recorder().note(
                "stale_notmod", "error",
                server_version=not_modified.version, client_version=version,
            )
            raise RuntimeError(
                "parameter server sent not-modified for version "
                f"{not_modified.version} but this client last saw "
                f"{version} (protocol violation)"
            )
        return tree


class ParameterServerUnavailable(ConnectionError):
    """The parameter server could not be reached after retries."""


class VersionUnavailable(RuntimeError):
    """A pinned pull asked for a version the server can no longer serve.

    The version-pinning plane (``rollout/``) reads historical snapshots
    out of the PS's WAL; the WAL keeps a bounded window, so a pin that
    outlived it is a *definitive* application answer — re-sending the
    same request cannot succeed, and callers (the rollout controller's
    rollback path) must pick a different pin, not retry."""

    def __init__(self, address: str, version: int):
        self.address = address
        self.version = int(version)
        super().__init__(
            f"parameter server at {address} cannot serve pinned version "
            f"{version} (not the live buffer and outside its WAL window)"
        )


class StaleDeltaRejected(RuntimeError):
    """The PS refused a pushed delta: staler than its admission bound.

    A *definitive* application-level answer, not a transport failure —
    re-sending the same delta can only be MORE stale, so nothing retries
    this. The right response (``async_engine._CommsPipeline`` implements
    it) is to drop the delta, re-pull fresh parameters, and sync more
    often. Carries the server's live ``version`` (the re-pull target),
    the measured ``lag``, and the ``max_staleness`` bound it crossed."""

    def __init__(self, address: str, version: int, lag: int,
                 max_staleness: int):
        self.address = address
        self.version = int(version)
        self.lag = int(lag)
        self.max_staleness = int(max_staleness)
        super().__init__(
            f"parameter server at {address} rejected the pushed delta: "
            f"staleness {lag} exceeds max_staleness={max_staleness} "
            f"(server now at version {version}; re-pull and sync more "
            "often)"
        )


def _raise_if_rejected(reply, address: str) -> None:
    """Surface a typed ``EPRJ`` push reply as ``StaleDeltaRejected``.
    Any other reply (the legacy ``b"ok"`` ack, an empty HTTP body)
    passes through untouched."""
    if isinstance(reply, (bytes, bytearray, memoryview)) \
            and wire.is_packed(reply):
        out = wire.decode(reply)
        if isinstance(out, wire.DeltaRejected):
            raise StaleDeltaRejected(address, out.version, out.lag,
                                     out.max_staleness)


def _retry_connect(fn, address: str, op: str, sleep=time.sleep):
    """Run ``fn`` retrying connection-level failures with backoff.

    Anything that indicates the server is *gone* (refused, reset, DNS,
    dial timeout) is retried then converted to ParameterServerUnavailable;
    application-level errors (HTTP 4xx/5xx → RuntimeError) propagate
    immediately. Callers must only pass an ``fn`` that is safe to run
    again (a pure read, or connection establishment) — see the module
    docstring's idempotency contract. ``sleep`` is injectable so tests
    assert the exact backoff schedule without real waiting.
    """
    last: Exception | None = None
    for delay in (*_RETRY_DELAYS, None):
        try:
            return fn()
        except (ConnectionError, socket.timeout, TimeoutError, OSError) as exc:
            last = exc
        if delay is None:
            break
        sleep(delay)
    raise ParameterServerUnavailable(
        f"parameter server at {address} unreachable during {op} "
        f"(retried {len(_RETRY_DELAYS)}x over ~{sum(_RETRY_DELAYS):.1f}s): {last}"
    ) from last


class LocalClient(BaseParameterClient):
    def __init__(self, buffer: ParameterBuffer, detector=None):
        """``detector``: the owning ``LocalServer``'s failure detector;
        when wired, the liveness surface (heartbeat/membership/deregister)
        is real bookkeeping even in-process — the elastic pool's monitor
        works identically across transports."""
        self._buffer = buffer
        self._detector = detector

    def get_parameters(self):
        with _ps_span("pull", "local"):
            return self._buffer.get()

    def update_parameters(self, delta) -> None:
        with _ps_span("push", "local"):
            self._buffer.apply_delta(delta)

    def heartbeat(self, worker_id: str) -> None:
        if self._detector is not None:
            self._detector.beat(worker_id)

    def membership(self) -> dict:
        return {} if self._detector is None else self._detector.membership()

    def deregister(self, worker_id: str) -> None:
        if self._detector is not None:
            self._detector.deregister(worker_id)

    def wait_barrier(self, tag: str, n: int, timeout: Optional[float] = None) -> None:
        pass  # in-process buffer == single host; nothing to synchronize


class _WireBarrierMixin:
    """PS-backed host barrier: arrive once, then poll the arrival count.

    Used for fit teardown across hosts. Polling the PS (instead of a
    device collective) tolerates arbitrary host drift — async workers can
    be minutes apart, far past collective-rendezvous deadlines.
    """

    def barrier_arrive(self, tag: str) -> int:
        raise NotImplementedError

    def barrier_count(self, tag: str) -> int:
        raise NotImplementedError

    def wait_barrier(self, tag: str, n: int, timeout: Optional[float] = None) -> None:
        """Arrive, then poll until ``n`` hosts arrived or ``timeout``
        (default ``$ELEPHAS_BARRIER_TIMEOUT``, 600s) — a dead peer host
        surfaces as a TimeoutError naming the barrier, not a silent hang
        (the reference relied on Spark killing the whole job)."""
        if timeout is None:
            raw = os.environ.get("ELEPHAS_BARRIER_TIMEOUT", "600")
            try:
                timeout = float(raw)
            except ValueError:
                # A typo'd env var must not crash teardown at the very
                # end of a fit — warn and take the default.
                warnings.warn(
                    f"ELEPHAS_BARRIER_TIMEOUT={raw!r} is not a number; "
                    "using the 600s default",
                    RuntimeWarning,
                    stacklevel=2,
                )
                timeout = 600.0
        self.barrier_arrive(tag)
        deadline = time.monotonic() + timeout
        poll = 0.02
        while time.monotonic() < deadline:
            if self.barrier_count(tag) >= n:
                return
            time.sleep(poll)
            poll = min(poll * 2, 0.5)
        final = self.barrier_count(tag)  # re-check: peer may have arrived
        if final >= n:                   # during the last sleep interval
            return
        raise TimeoutError(
            f"barrier {tag!r}: {final}/{n} hosts after {timeout}s "
            "— a peer host likely died; restart the job from the latest checkpoint"
        )


class HttpClient(_WireBarrierMixin, BaseParameterClient):
    """``http.client`` against ``GET /parameters`` / ``POST /update``.

    Dialing gets ``_CONNECT_TIMEOUT`` per attempt (a blackholed host
    fails in ~2s, not ``timeout``); the socket is then re-budgeted to
    ``timeout`` for the transfer. Only the dial retries — see the
    module docstring's idempotency/wedge contract.
    """

    def __init__(self, master_url: str, timeout: float = 60.0,
                 auth_key: Optional[bytes] = None,
                 codec: Optional[str] = None,
                 push_quantize: Optional[str] = None):
        """``codec``: 'packed' (default) or 'pickle' (see
        ``_resolve_codec``); responses self-describe by magic, so a
        packed client degrades transparently against a legacy pickle
        server. ``push_quantize``: 'bf16'|'f16' halves push bytes by
        casting float deltas on the wire (lossy — see the README
        convergence caveat; pulls are always full precision)."""
        host, port = master_url.rsplit(":", 1)
        self.master_url = master_url
        self._addr = (host, int(port))
        self.timeout = timeout
        self.auth_key = auth_key  # HMAC secret; see HttpServer auth docs
        self.codec = _resolve_codec(codec)
        self.push_quantize = push_quantize
        self._pull_cache = _PullCache()
        # Stable worker identity stamped onto pushes for the PS's
        # staleness ledger; owners (the elastic pool's client factory)
        # set it after construction. None → pushes go unstamped.
        self.worker_id: Optional[str] = None
        # Self-reported adaptive units-per-push (the comms pipeline's
        # ratchet keeps it current) — telemetry for the PS ledger's
        # SYNC column, never part of the admission decision.
        self.sync_interval: Optional[float] = None

    def _connect_once(self, transfer_timeout: Optional[float] = None) -> http.client.HTTPConnection:
        conn = http.client.HTTPConnection(*self._addr, timeout=_CONNECT_TIMEOUT)
        conn.connect()  # fail the dial fast; transfers get the long budget
        conn.sock.settimeout(
            self.timeout if transfer_timeout is None else transfer_timeout
        )
        return conn

    def _roundtrip(self, conn, method: str, path: str, payload,
                   extra_headers: Optional[dict] = None) -> bytes:
        try:
            headers = {"Content-Type": "application/octet-stream"} if payload else {}
            if extra_headers:
                headers.update(extra_headers)
            nonce = b""
            if self.auth_key is not None:
                nonce = os.urandom(16)
                ts = repr(time.time())
                headers["X-Elephas-Nonce"] = nonce.hex()
                headers["X-Elephas-TS"] = ts
                headers["X-Elephas-Auth"] = socket_utils.frame_mac(
                    self.auth_key,
                    method.encode() + path.encode() + nonce + ts.encode()
                    + (payload or b""),
                ).hex()
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status >= 400:
                raise RuntimeError(
                    f"parameter server returned HTTP {resp.status} for {path}"
                )
            if self.auth_key is not None and path != "/health":
                # Verify the server's signature — bound to OUR nonce, so
                # a captured response can't be replayed into a different
                # exchange — BEFORE any unpickle of the body.
                want = socket_utils.frame_mac(self.auth_key, nonce + body).hex()
                if not hmac.compare_digest(
                    resp.headers.get("X-Elephas-Auth", ""), want
                ):
                    raise RuntimeError(
                        f"parameter server response for {path} failed HMAC "
                        "verification (wrong or missing auth key)"
                    )
            return body
        finally:
            conn.close()

    def _call(self, method: str, path: str, payload, op: str,
              headers: Optional[dict] = None) -> bytes:
        """Dial with the retry budget, then ONE transfer attempt.

        Only the dial phase retries: a refused/blackholed host is the
        transient case worth ~3s of patience. Once connected, a transfer
        failure means the server is wedged (accepting but not serving) or
        died mid-request — retrying would stall ``timeout``-per-attempt
        (and for writes, risk double-apply), so it raises immediately.
        """
        conn = _retry_connect(self._connect_once, self.master_url, op)
        try:
            return self._roundtrip(conn, method, path, payload,
                                   extra_headers=headers)
        # HTTPException covers a server that closes mid-response (e.g.
        # BadStatusLine/RemoteDisconnected during PS shutdown).
        except (ConnectionError, socket.timeout, TimeoutError, OSError,
                http.client.HTTPException) as exc:
            raise ParameterServerUnavailable(
                f"parameter server at {self.master_url} failed after the {op} "
                f"request was sent (transfer not retried — server wedged or "
                f"died mid-request): {exc}"
            ) from exc

    def _get(self, path: str, op: str, headers: Optional[dict] = None) -> bytes:
        return self._call("GET", path, None, op, headers=headers)

    def _post(self, path: str, payload: bytes, op: str,
              headers: Optional[dict] = None) -> bytes:
        return self._call("POST", path, payload, op, headers=headers)

    def get_parameters(self):
        with _ps_span("pull", "http") as sp:
            headers = {}
            tc = _span_trace(sp)
            if tc is not None:
                # Propagate our span identity: the server's handle span
                # adopts it and becomes this pull's child in the merge.
                headers["X-Elephas-Trace"] = f"{tc.trace_id}-{tc.span_id}"
            if self.codec == "packed":
                headers["X-Elephas-Codec"] = "packed"
                known = self._pull_cache.known()
                if isinstance(known, tuple):
                    # (boot, version): the server only answers
                    # not-modified when BOTH match — version alone can
                    # alias a previous server life after warm restart.
                    headers["X-Elephas-Boot"] = known[0]
                    headers["X-Elephas-Version"] = str(known[1])
                elif known is not None:
                    headers["X-Elephas-Version"] = str(known)
            body = self._get("/parameters", "get_parameters",
                             headers=headers or None)
            # Magic negotiation: a legacy server ignores our codec header
            # and replies pickle — decode whatever actually came back.
            if wire.is_packed(body):
                out = wire.decode(body)
                if isinstance(out, wire.NotModified):
                    if sp:
                        sp.note(codec="packed", payload_bytes=len(body),
                                cache_hit=True)
                    return self._pull_cache.resolve(out)
                self._pull_cache.store(out.version, out.tree, boot=out.boot)
                if sp:
                    sp.note(codec="packed", payload_bytes=len(body))
                return out.tree
            if sp:
                sp.note(codec="pickle", payload_bytes=len(body))
            return wire.decode_pickle(body)

    def known_version(self) -> Optional[int]:
        """Version of the last full-body pull this client cached (None
        before any pull) — the subscription plane's position probe."""
        return self._pull_cache.known_version()

    def update_parameters(self, delta) -> None:
        with _ps_span("push", "http") as sp:
            delta = jax.device_get(delta)
            seen = self._pull_cache.known_version()
            payload, codec = _encode_push(delta, self.codec,
                                          self.push_quantize,
                                          seen_version=seen,
                                          worker=self.worker_id,
                                          sync_interval=self.sync_interval)
            if isinstance(payload, wire.Frames):
                # http.client needs one body buffer; the zero-copy chunk
                # path is the socket transport's.
                payload = payload.tobytes()
            if sp:
                sp.note(codec=codec, payload_bytes=len(payload),
                        quantize=self.push_quantize)
            headers = {}
            tc = _span_trace(sp)
            if tc is not None:
                headers["X-Elephas-Trace"] = f"{tc.trace_id}-{tc.span_id}"
            # Staleness stamps ride as headers too, so a pickle-codec
            # body (or a packed→pickle fallback) still declares what it
            # trained against; the server prefers the in-frame stamps.
            if seen is not None:
                headers["X-Elephas-Seen-Version"] = str(seen)
            if self.worker_id is not None:
                headers["X-Elephas-Worker"] = str(self.worker_id)
            if self.sync_interval is not None:
                headers["X-Elephas-Sync-Interval"] = str(self.sync_interval)
            body = self._post("/update", payload, "update_parameters",
                              headers=headers or None)
            # An admission rejection comes back as a typed frame in the
            # (normally empty) 200 body — surface it as the exception
            # the comms pipeline's ratchet acts on.
            _raise_if_rejected(body, self.master_url)

    def get_parameters_pinned(self, version: int):
        """Snapshot read of one EXACT historical version (WAL-backed).

        Bypasses the version-gated pull cache entirely — a pinned read
        is a point lookup for rollback/A-B, never "the latest", so it
        must not poison the cache's notion of the current position.
        Raises ``VersionUnavailable`` when the server no longer holds
        that version (live buffer moved on AND the WAL pruned it)."""
        with _ps_span("pull", "http") as sp:
            headers = {"X-Elephas-Codec": "packed",
                       "X-Elephas-Pinned": str(int(version))}
            try:
                body = self._get("/parameters", "get_parameters_pinned",
                                 headers=headers)
            except RuntimeError as exc:
                if "HTTP 404" in str(exc):
                    raise VersionUnavailable(self.master_url,
                                             version) from exc
                raise
            out = wire.decode(body)
            if out.version != int(version):
                raise RuntimeError(
                    f"pinned pull for version {version} answered with "
                    f"version {out.version} (protocol violation)")
            if sp:
                sp.note(codec="packed", payload_bytes=len(body),
                        pinned=int(version))
            return out.tree

    def health(self) -> bool:
        """One non-retried probe of ``GET /health``, bounded end-to-end by
        ``_CONNECT_TIMEOUT`` (a wedged-but-accepting server must not stall
        the liveness check for the full transfer budget)."""
        try:
            return self._roundtrip(
                self._connect_once(transfer_timeout=_CONNECT_TIMEOUT),
                "GET", "/health", None,
            ) == b"ok"
        except Exception:
            return False

    def heartbeat(self, worker_id: str) -> None:
        self._post(f"/heartbeat/{worker_id}", b"", "heartbeat")

    def membership(self) -> dict:
        return json.loads(self._get("/membership", "membership"))

    def deregister(self, worker_id: str) -> None:
        self._post(f"/deregister/{worker_id}", b"", "deregister")

    def shard_info(self) -> Optional[dict]:
        """``{digest, shard, k, boot}`` from a shard-group member, None
        from a standalone server (404 on the pre-group route)."""
        try:
            return json.loads(self._get("/shardinfo", "shard_info"))
        except RuntimeError:
            return None

    def barrier_arrive(self, tag: str) -> int:
        return int(self._post(f"/barrier/{tag}", b"", "barrier_arrive"))

    def barrier_count(self, tag: str) -> int:
        return int(self._get(f"/barrier/{tag}", "barrier_count"))


def make_client(
    mode: str, address: str, auth_key: Optional[bytes] = None,
    codec: Optional[str] = None, push_quantize: Optional[str] = None,
) -> BaseParameterClient:
    """Client for a parameter server reachable at ``address`` ("ip:port").

    The cross-host worker path: hosts that did not start the server dial
    the address host 0 broadcast (reference topology — every worker talks
    to the one driver PS, SURVEY.md §3.2). ``auth_key``: the DCN-broadcast
    HMAC secret for authenticated multi-host wire traffic. ``codec`` /
    ``push_quantize``: wire codec knobs (see ``HttpClient``).
    """
    if mode == "http":
        return HttpClient(address, auth_key=auth_key, codec=codec,
                          push_quantize=push_quantize)
    if mode == "socket":
        return SocketClient(address, auth_key=auth_key, codec=codec,
                            push_quantize=push_quantize)
    raise ValueError(f"no wire client for parameter_server_mode={mode!r}")


class SocketClient(_WireBarrierMixin, BaseParameterClient):
    """Persistent framed-TCP connection (one per worker thread)."""

    def __init__(self, master_url: str, timeout: float = 60.0,
                 auth_key: Optional[bytes] = None,
                 codec: Optional[str] = None,
                 push_quantize: Optional[str] = None):
        """``codec``: 'packed' (default) or 'pickle'. Unlike HTTP there
        is no transparent downgrade — a legacy socket server closes the
        connection on the packed frame kinds — so dial old servers with
        ``codec='pickle'`` (or ``ELEPHAS_PS_CODEC=pickle``).
        ``push_quantize``: 'bf16'|'f16' lossy delta casting (README
        caveat); ignored on the pickle codec."""
        host, port = master_url.rsplit(":", 1)
        self.master_url = master_url
        self._addr = (host, int(port))
        self.timeout = timeout
        self.auth_key = auth_key  # HMAC frame secret (utils.sockets)
        self.codec = _resolve_codec(codec)
        self.push_quantize = push_quantize
        self._pull_cache = _PullCache()
        # See HttpClient.worker_id: staleness-ledger identity stamp.
        self.worker_id: Optional[str] = None
        # See HttpClient.sync_interval: SYNC-column telemetry stamp.
        self.sync_interval: Optional[float] = None
        self._sock = None
        self._lock = threading.Lock()  # one in-flight request per connection

    def _connection(self) -> socket.socket:
        if self._sock is None:
            def attempt():
                sock = socket.create_connection(self._addr, timeout=_CONNECT_TIMEOUT)
                sock.settimeout(self.timeout)
                # Strict request/reply framing: Nagle + delayed-ACK only
                # adds ~40 ms stalls to small frames (version-gated pull
                # requests, push acks).
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return sock

            self._sock = _retry_connect(attempt, self.master_url, "connect")
        return self._sock

    def _roundtrip(self, frame, op: str, idempotent: bool):
        """Send one frame, read one reply.

        ``idempotent`` round-trips (reads) get ONE transparent
        reconnect-and-resend if the pooled connection died between calls
        (PS restart); writes are never re-sent after a send attempt — the
        server may already have applied them (module docstring).
        """
        for retry in (idempotent, False):
            sock = self._connection()
            try:
                nonce = socket_utils.send(sock, frame, key=self.auth_key)  # lock-ok: the conn lock exists to serialize this socket (one in-flight request)
                # Reply MAC is bound to OUR request nonce (mirrors the
                # HTTP transport): a captured server response can't be
                # replayed into a different exchange.
                return socket_utils.receive(sock, key=self.auth_key, bind=nonce)  # lock-ok: reply read is the second half of the serialized exchange
            except (socket.timeout, TimeoutError) as exc:
                # Read timeout on an ESTABLISHED connection: the server is
                # wedged, not restarting — another ``timeout``-long attempt
                # would stall, so fail fast (module docstring contract).
                self._drop_connection(sock)
                raise ParameterServerUnavailable(
                    f"parameter server at {self.master_url} timed out during "
                    f"{op} (wedged — not retried): {exc}"
                ) from exc
            except (ConnectionError, OSError) as exc:
                # Reset/EPIPE: a pooled connection died between calls (PS
                # restart). Reads get one transparent reconnect; writes
                # don't — the server may already have applied them.
                self._drop_connection(sock)
                if not retry:
                    raise ParameterServerUnavailable(
                        f"parameter server at {self.master_url} dropped the "
                        f"connection during {op}"
                        + ("" if idempotent else " (write not re-sent: a "
                           "duplicate would double-apply)")
                        + f": {exc}"
                    ) from exc

    def _drop_connection(self, sock) -> None:
        self._sock = None
        try:
            sock.close()
        except OSError:
            pass

    def get_parameters(self):
        with _ps_span("pull", "socket") as sp, self._lock:
            if self.codec != "packed":
                tree = self._roundtrip(("g", None), "get_parameters",
                                       idempotent=True)
                if sp:
                    sp.note(codec="pickle")
                return tree
            # known is (boot, version) from a boot-stamping server, a
            # bare int against legacy peers, or None on a cold cache —
            # the server only answers not-modified for a matching pair.
            known = self._pull_cache.known()
            tc = _span_trace(sp)
            # Trace context rides as an OPTIONAL third element — untraced
            # runs keep the legacy 2-tuple a pre-PR-6 server expects.
            frame = ("G", known) if tc is None else ("G", known, tuple(tc))
            reply = self._roundtrip(frame, "get_parameters",
                                    idempotent=True)
            if not isinstance(reply, (bytes, bytearray, memoryview)):
                raise RuntimeError(
                    "parameter server sent a non-packed reply to a packed "
                    "pull — is it a pre-packed-codec server? dial it with "
                    "codec='pickle' (or ELEPHAS_PS_CODEC=pickle)"
                )
            out = wire.decode(reply)
            if isinstance(out, wire.NotModified):
                if sp:
                    sp.note(codec="packed", payload_bytes=len(reply),
                            cache_hit=True)
                return self._pull_cache.resolve(out)
            self._pull_cache.store(out.version, out.tree, boot=out.boot)
            if sp:
                sp.note(codec="packed", payload_bytes=len(reply))
            return out.tree

    def known_version(self) -> Optional[int]:
        """See ``HttpClient.known_version``."""
        return self._pull_cache.known_version()

    def update_parameters(self, delta) -> None:
        with _ps_span("push", "socket") as sp:
            delta = jax.device_get(delta)
            tc = _span_trace(sp)
            # Legacy-pickle frames carry the context as an optional third
            # element; packed frames carry it in their own header ("tc").
            frame = ("u", delta) if tc is None else ("u", delta, tuple(tc))
            codec, nbytes = "pickle", None
            if self.codec == "packed":
                try:
                    # The Frames go to the socket as scatter-gather
                    # chunks (no pickle wrapper, no concatenation); the
                    # server recognizes a raw packed frame as a push by
                    # its magic. Unpackable structures ride the legacy
                    # ('u', delta) frame instead.
                    frames = wire.encode_tree(
                        delta, quantize=self.push_quantize, trace=tc,
                        seen_version=self._pull_cache.known_version(),
                        worker=self.worker_id,
                        sync_interval=self.sync_interval)
                    frame, codec, nbytes = frames, "packed", frames.nbytes
                except wire.WireFormatError:
                    pass
            with self._lock:
                reply = self._roundtrip(frame, "update_parameters",
                                        idempotent=False)
            # The ack is b"ok" — unless the admission policy refused the
            # delta, in which case the reply IS the typed EPRJ frame.
            _raise_if_rejected(reply, self.master_url)
            if sp:
                sp.note(codec=codec, payload_bytes=nbytes,
                        quantize=self.push_quantize)

    def get_parameters_pinned(self, version: int):
        """Snapshot read of one EXACT historical version (WAL-backed,
        frame kind ``"V"``). Bypasses the pull cache — see
        ``HttpClient.get_parameters_pinned``. A ``None`` reply is the
        server's typed "don't have it" answer → ``VersionUnavailable``."""
        with _ps_span("pull", "socket") as sp, self._lock:
            reply = self._roundtrip(("V", int(version)),
                                    "get_parameters_pinned",
                                    idempotent=True)
            if reply is None:
                raise VersionUnavailable(self.master_url, version)
            if not isinstance(reply, (bytes, bytearray, memoryview)):
                raise RuntimeError(
                    "parameter server sent a non-packed reply to a pinned "
                    "pull — is it a pre-rollout server?")
            out = wire.decode(reply)
            if out.version != int(version):
                raise RuntimeError(
                    f"pinned pull for version {version} answered with "
                    f"version {out.version} (protocol violation)")
            if sp:
                sp.note(codec="packed", payload_bytes=len(reply),
                        pinned=int(version))
            return out.tree

    def health(self) -> bool:
        """Liveness probe: a barrier *count* on a FRESH connection.

        A stopped server keeps serving already-accepted connections until
        they close, so probing the pooled one would report a dead PS
        alive; dialing anew answers "would a new worker get in?".
        """
        try:
            sock = socket.create_connection(self._addr, timeout=_CONNECT_TIMEOUT)
            try:
                sock.settimeout(_CONNECT_TIMEOUT)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                nonce = socket_utils.send(sock, ("c", "health"), key=self.auth_key)
                socket_utils.receive(sock, key=self.auth_key, bind=nonce)
            finally:
                sock.close()
            return True
        except Exception:
            return False

    def heartbeat(self, worker_id: str) -> None:
        # Idempotent by nature: a duplicated beat just refreshes the same
        # liveness timestamp, so the transparent-reconnect path is safe.
        with self._lock:
            self._roundtrip(("h", worker_id), "heartbeat", idempotent=True)

    def membership(self) -> dict:
        with self._lock:
            return self._roundtrip(("m", None), "membership", idempotent=True)

    def deregister(self, worker_id: str) -> None:
        # Also idempotent: deregistering an absent worker is a no-op.
        with self._lock:
            self._roundtrip(("d", worker_id), "deregister", idempotent=True)

    def shard_info(self) -> Optional[dict]:
        """``{digest, shard, k, boot}`` from a shard-group member. A
        pre-group server closes the connection on the unknown frame kind,
        which surfaces here as None (the handshake then reports "no
        shard map" rather than a transport error)."""
        with self._lock:
            try:
                return self._roundtrip(("i", None), "shard_info",
                                       idempotent=True)
            except ParameterServerUnavailable:
                return None

    def barrier_arrive(self, tag: str) -> int:
        with self._lock:
            return self._roundtrip(("b", tag), "barrier_arrive", idempotent=False)

    def barrier_count(self, tag: str) -> int:
        with self._lock:
            return self._roundtrip(("c", tag), "barrier_count", idempotent=True)

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
