"""Parameter exchange layer (reference L2 — SURVEY.md §1).

Reference: ``elephas/parameter/{base,server,client}.py`` — a Flask HTTP or
raw-socket parameter server on the Spark driver, pickled weight lists over
the network, 2 network hops per worker per ``frequency`` unit.

TPU-native redesign: the canonical store is an HBM-resident
``ParameterBuffer`` (weights live on a chip, updates are jitted on-device
adds). Transports are pluggable on top for cross-host parity:

- ``local``  — in-process buffer handle (single-host pods; zero copies
  off-device except the pull into each worker chip),
- ``http``   — stdlib ThreadingHTTPServer speaking the reference's
  GET /parameters, POST /update protocol,
- ``socket`` — length-prefixed frames with the reference's
  ``'g'``/``'u'`` message kinds.

Wire payloads default to the packed zero-copy codec (``wire.py``:
contiguous tensor region + small JSON header, version-gated not-modified
replies, optional bf16/f16 delta quantization) with magic-byte
negotiation back to the reference's pickle for legacy peers.

``group.py`` scales the wire transports horizontally: a deterministic
``ShardPlan`` partitions the tree across K server processes, a
``ShardedParameterClient`` scatters/gathers concurrently, and a
WAL-streamed warm standby per shard turns PS failover into a promotion
(``ShardGroup``).
"""

from elephas_tpu.parameter import wire  # noqa: F401

from elephas_tpu.parameter.base import (  # noqa: F401
    BaseParameterClient,
    BaseParameterServer,
)
from elephas_tpu.parameter.buffer import ParameterBuffer  # noqa: F401
from elephas_tpu.parameter.server import (  # noqa: F401
    HttpServer,
    LocalServer,
    SocketServer,
    make_server,
)
from elephas_tpu.parameter.client import (  # noqa: F401
    HttpClient,
    LocalClient,
    SocketClient,
)
from elephas_tpu.parameter.group import (  # noqa: F401
    FencedPrimaryError,
    GroupDirectory,
    ShardGroup,
    ShardGroupError,
    ShardMapMismatch,
    ShardPlan,
    ShardedParameterClient,
    WalStreamer,
)
