import sys

from elephas_tpu.analysis.cli import main

sys.exit(main())
