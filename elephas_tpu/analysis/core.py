"""Shared machinery for the static-analysis subsystem.

Every analyzer in ``elephas_tpu/analysis/`` is a :class:`Rule` over a
:class:`Repo`: the repo parses each source file ONCE into a
:class:`SourceFile` (source + line table + AST) and hands the same
object to every rule, so adding a rule costs one AST walk, not one
parse. Rules report :class:`Finding`\\ s — including findings a pragma
SUPPRESSED (``suppressed=True``), which is what lets the dead-pragma
rule prove an escape comment still earns its keep.

Pragma machinery: each rule names the escape pragma that silences it
(``# host-ok``, ``# lock-ok``, …). The legacy rules match the pragma
substring anywhere on the flagged line (historical contract, kept);
:func:`comment_pragmas` tokenizes a file and returns only REAL comment
pragmas, which is what the dead-pragma rule audits — a pragma mentioned
inside a string literal is documentation, not an escape.

The package never imports jax (rules read source, they don't run it),
so the CLI stays usable on hosts without an accelerator stack.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Every escape pragma any rule honors — the vocabulary the dead-pragma
#: rule audits. Grow this WITH the rule that honors the new pragma.
PRAGMAS: Tuple[str, ...] = (
    "host-ok",
    "clock-ok",
    "pickle-ok",
    "metric-ok",
    "kind-ok",
    "route-ok",
    "pool-ok",
    "lock-ok",
)


@dataclass
class Finding:
    """One analyzer hit: fired (``suppressed=False``) or pragma-escaped."""

    rule: str
    path: str
    lineno: int
    ident: str            # short identifier of what fired (call, lock, …)
    line: str             # the source line, verbatim
    message: str          # fully rendered human message
    suppressed: bool = False
    chain: Tuple[str, ...] = ()   # witness path (interprocedural rules)

    def render(self) -> str:
        head = f"{self.path}:{self.lineno}: [{self.rule}] {self.message}"
        body = f"\n    {self.line.strip()}" if self.line.strip() else ""
        steps = "".join(f"\n    -> {s}" for s in self.chain)
        return head + body + steps

    def as_json(self) -> dict:
        out = {
            "rule": self.rule,
            "path": self.path,
            "lineno": self.lineno,
            "ident": self.ident,
            "message": self.message,
            "suppressed": self.suppressed,
        }
        if self.chain:
            out["chain"] = list(self.chain)
        return out


class SourceFile:
    """Parse-once view of one module, shared by every rule."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel                     # repo-relative, for messages
        self.source = path.read_text()
        self.lines = self.source.splitlines()
        self._tree: Optional[ast.Module] = None
        self._comment_pragmas: Optional[Dict[int, List[str]]] = None

    @property
    def tree(self) -> ast.Module:
        if self._tree is None:
            self._tree = ast.parse(self.source, filename=str(self.path))
        return self._tree

    def line(self, lineno: int) -> str:
        return self.lines[lineno - 1] if 0 < lineno <= len(self.lines) else ""

    def has_pragma(self, lineno: int, pragma: str) -> bool:
        """Legacy contract: the pragma substring anywhere on the line."""
        return pragma in self.line(lineno)

    def comment_pragmas(self) -> Dict[int, List[str]]:
        """``{lineno: [pragmas]}`` for REAL comment tokens only.

        Tokenized, not substring-matched, so a pragma named inside a
        string literal (e.g. a lint message template) is invisible here.
        Tokenize errors fall back to empty — an unparsable file already
        fails every AST rule loudly.
        """
        if self._comment_pragmas is None:
            found: Dict[int, List[str]] = {}
            try:
                toks = tokenize.generate_tokens(
                    io.StringIO(self.source).readline)
                for tok in toks:
                    if tok.type != tokenize.COMMENT:
                        continue
                    for pragma in PRAGMAS:
                        # anchored at the comment's start: an escape is
                        # written `# pragma: reason`; a pragma named
                        # mid-comment (docs discussing the pragma) is
                        # commentary, not an escape.
                        if re.match(rf"#+\s*{re.escape(pragma)}\b",
                                    tok.string):
                            found.setdefault(tok.start[0], []).append(pragma)
            except (tokenize.TokenError, IndentationError, SyntaxError):
                pass
            self._comment_pragmas = found
        return self._comment_pragmas


class Repo:
    """Root paths + the shared :class:`SourceFile` cache."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self.pkg = self.root / "elephas_tpu"
        self.scripts = self.root / "scripts"
        self._cache: Dict[Path, SourceFile] = {}

    def file(self, path: Path) -> SourceFile:
        path = Path(path)
        sf = self._cache.get(path)
        if sf is None:
            try:
                rel = str(path.relative_to(self.root))
            except ValueError:
                rel = str(path)
            sf = SourceFile(path, rel)
            self._cache[path] = sf
        return sf

    def walk(self, base: Path, recursive: bool = True,
             exclude: Sequence[str] = ()) -> List[SourceFile]:
        if not base.is_dir():
            return []
        pattern = "*.py"
        paths = base.rglob(pattern) if recursive else base.glob(pattern)
        return [self.file(p) for p in sorted(paths)
                if p.name not in exclude and "__pycache__" not in p.parts]

    def package_files(self) -> List[SourceFile]:
        return self.walk(self.pkg)

    def scripts_files(self) -> List[SourceFile]:
        return self.walk(self.scripts, recursive=False)


class Rule:
    """One analyzer. Subclasses set ``name``/``pragma``/``describe`` and
    implement :meth:`run` returning every finding, suppressed included
    (the driver separates violations from escapes)."""

    #: registry identity, kebab-case
    name: str = ""
    #: escape pragma this rule honors ("" = not escapable)
    pragma: str = ""
    #: one-line description for --list-rules / README
    describe: str = ""

    def run(self, repo: Repo) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def scope(self, repo: Repo) -> List[SourceFile]:
        """Files this rule scans — the dead-pragma rule audits a
        pragma only inside the scopes of the rules honoring it."""
        return []


def violations(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if not f.suppressed]


def suppressions(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if f.suppressed]
