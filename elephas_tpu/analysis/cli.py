"""Driver for the analysis subsystem: registry, report, CLI.

``python -m elephas_tpu.analysis`` runs every registered rule over the
repo and exits non-zero on any unsuppressed violation — the same
contract as the old ``scripts/lint_blocking.py`` (which remains as a
shim over the legacy rules), extended with the concurrency analyzers
and the dead-pragma audit.

``--json`` emits the full machine-readable report; ``--write
ANALYSIS.json`` persists it. The committed ``ANALYSIS.json`` carries a
``rows`` table (one row per rule: violations + suppression counts, plus
a ``total`` row with the lock-graph shape) that ``scripts/bench_gate.py
--analysis`` diffs against a fresh run — so a new violation, a silently
vanished suppression, or a fresh lock-order cycle each fail the gate
mechanically.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from elephas_tpu.analysis.core import (Finding, Repo, Rule, suppressions,
                                       violations)
from elephas_tpu.analysis.legacy import LEGACY_RULES
from elephas_tpu.analysis.locks import (BlockingUnderLockRule, LockAnalysis,
                                        LockOrderRule, get_analysis)
from elephas_tpu.analysis.pragmas import DeadPragmaRule


def default_root() -> Path:
    return Path(__file__).resolve().parents[2]


def build_rules() -> Tuple[Rule, ...]:
    """The full registry, dead-pragma last (it audits the others)."""
    base: Tuple[Rule, ...] = LEGACY_RULES + (
        LockOrderRule(), BlockingUnderLockRule())
    return base + (DeadPragmaRule(base),)


def run_rules(repo: Repo, rules: Optional[Sequence[Rule]] = None
              ) -> Dict[str, List[Finding]]:
    """Run every rule once; the dead-pragma rule consumes the others'
    findings instead of re-running them."""
    rules = list(rules) if rules is not None else list(build_rules())
    out: Dict[str, List[Finding]] = {}
    shared: List[Finding] = []
    for rule in rules:
        if isinstance(rule, DeadPragmaRule):
            found = rule.run(repo, findings=shared)
        else:
            found = rule.run(repo)
        out[rule.name] = found
        shared.extend(found)
    return out


def build_report(root: Optional[Path] = None) -> dict:
    root = Path(root) if root is not None else default_root()
    repo = Repo(root)
    rules = build_rules()
    by_rule = run_rules(repo, rules)
    la: LockAnalysis = get_analysis(repo)

    rows: List[dict] = []
    all_viol: List[Finding] = []
    all_supp: List[Finding] = []
    for rule in rules:
        found = by_rule[rule.name]
        v, s = violations(found), suppressions(found)
        all_viol.extend(v)
        all_supp.extend(s)
        rows.append({
            "section": rule.name,
            "violations": len(v),
            "suppressions": len(s),
        })
    graph = la.export()
    rows.append({
        "section": "total",
        "violations": len(all_viol),
        "suppressions": len(all_supp),
        "lock_cycles": len(la.cycles()),
        "locks": len(graph["locks"]),
        "lock_edges": len(graph["edges"]),
    })
    return {
        "root": str(root),
        "rules": [
            {"name": r.name, "pragma": r.pragma, "describe": r.describe}
            for r in rules
        ],
        "rows": rows,
        "violations": [f.as_json() for f in all_viol],
        "suppressions": [f.as_json() for f in all_supp],
        "lock_graph": graph,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="elephas-analyze",
        description=("Static analysis over the elephas_tpu package: "
                     "legacy lint domains + lock-order, "
                     "blocking-under-lock, and dead-pragma audits."))
    ap.add_argument("--root", default=None,
                    help="repo root (default: derived from the package)")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("--write", metavar="PATH", default=None,
                    help="write the report (e.g. ANALYSIS.json)")
    ap.add_argument("--list-rules", action="store_true",
                    help="list registered rules and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in build_rules():
            esc = f"# {r.pragma}" if r.pragma else "(not escapable)"
            print(f"{r.name:18s} {esc:16s} {r.describe}")
        return 0

    root = Path(args.root) if args.root else default_root()
    report = build_report(root)
    text = json.dumps(report, indent=1)
    if args.write:
        with open(args.write, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        viol = report["violations"]
        for v in sorted(viol, key=lambda f: (f["path"], f["lineno"])):
            print(f"{v['path']}:{v['lineno']}: [{v['rule']}] "
                  f"{v['message']}")
            for step in v.get("chain", []):
                print(f"    -> {step}")
        total = report["rows"][-1]
        if not viol:
            print(f"analysis: {root} clean "
                  f"({len(report['rules'])} rules, "
                  f"{total['suppressions']} suppressions, "
                  f"{total['locks']} locks, "
                  f"{total['lock_edges']} order edges, "
                  f"{total['lock_cycles']} cycles)")
    return 1 if report["violations"] else 0


if __name__ == "__main__":
    sys.exit(main())
