"""Concurrency analyzers: the package-wide lock-acquisition graph.

Two rules share one extraction pass:

**lock-order** — every ``with <lock>:`` / ``.acquire()`` site in the
package is extracted and named by its defining ``(module, class, attr)``
(aliases through ``self._lock``-style fields, module-level locks, and
function locals all resolve to one identity per lock object class).
Nested acquisitions — lexical, AND through method calls resolved across
modules — become directed edges ``A -> B`` ("A is held while B is
acquired"). A cycle in that graph is deadlock potential: two threads
entering the cycle from different nodes can block each other forever.
The rule fails on every cycle with the full witness path (file:line of
each acquisition / call hop). A ``# lock-ok`` pragma on the inner
acquisition or call line excludes that edge (recorded as a suppression,
so the dead-pragma rule audits it).

**lock-blocking** — blocking operations executed while a lock is held:
socket sends/recvs, ``fsync``/``flush``, ``time.sleep``, wire
encode/decode, HTTP request/dispatch, thread joins, and ``.wait()`` on
a foreign condition. This is the PR-4/PR-14 bug class (version bumped
outside the buffer write lock; kill() journaling before severing
connections): holding a registry/buffer lock across I/O turns every
reader into a convoy and every flaky peer into a server stall. Direct
hits are flagged at the blocking line; a call made under a lock to a
method whose body blocks (one level, pragma-free sites only) is flagged
at the call site with the chain. Escape: ``# lock-ok`` with a reason.

Resolution is deliberately conservative — an unresolvable callee adds
no edge (a missed edge is a missed warning; an invented edge is a false
deadlock). Method calls resolve through ``self``, through attribute
types inferred from ``self.x = ClassName(...)`` constructor
assignments, and through a global method-name match only when exactly
one class in the package defines that name.

The same extraction also cross-checks the RUNTIME sanitizer's naming:
a ``make_lock("…")``/``make_condition("…")`` literal that doesn't match
the statically derived identity of the field it's assigned to is a
violation — the static graph and the sanitizer must speak one language.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from elephas_tpu.analysis.core import Finding, Repo, Rule, SourceFile

LOCK_PRAGMA = "lock-ok"

# threading.<factory>() and the sanitizer's wrappers.
_THREADING_FACTORIES = {"Lock": "lock", "RLock": "rlock",
                        "Condition": "condition"}
_SANITIZER_FACTORIES = {"make_lock": "lock", "make_rlock": "rlock",
                        "make_condition": "condition"}
_LOCK_CLASS_CTORS = {"RWLock": "rwlock"}
_NULL_LOCK_CTORS = {"NullLock"}

_ACQUIRE_METHODS = {"acquire", "acquire_read", "acquire_write"}
_CTX_ACQUIRE_METHODS = {"reading", "writing"}
_LOCK_NOISE_METHODS = {"release", "locked", "notify", "notify_all",
                       "notify_one"}

# -- blocking-operation matchers --------------------------------------------

# attr-call names flagged on ANY receiver.
_BLOCK_ANY_RECV = {
    "sendall": "socket send", "sendto": "socket send",
    "recv": "socket recv", "recv_into": "socket recv",
    "recvfrom": "socket recv", "accept": "socket accept",
    "connect": "socket connect", "connect_ex": "socket connect",
    "fsync": "fsync", "fdatasync": "fsync",
    "flush": "flush",
    "urlopen": "http request", "getresponse": "http response",
    "serve_forever": "http dispatch", "handle_request": "http dispatch",
    "receive": "socket recv",
}
# attr-call names flagged only for specific receiver module names.
_BLOCK_MODULE_RECV = {
    ("time", "sleep"): "sleep",
    ("os", "fsync"): "fsync",
    ("os", "fdatasync"): "fsync",
    ("select", "select"): "select",
    ("subprocess", "run"): "subprocess",
    ("subprocess", "check_call"): "subprocess",
    ("subprocess", "check_output"): "subprocess",
}
# wire codec entry points (attr or imported bare call).
_WIRE_NAMES = {"encode_tree", "decode_tree", "decode_payload",
               "decode_payload_traced", "encode_pickle", "decode_pickle"}
# bare names that count when they were imported from somewhere.
_BLOCK_BARE_IMPORTED = {
    "send": "socket send", "recv": "socket recv", "receive": "socket recv",
    "urlopen": "http request", "sleep": "sleep", "fsync": "fsync",
}
_BLOCK_BARE_IMPORTED.update({n: "wire codec" for n in _WIRE_NAMES})
# .send( on any receiver is too noisy only for generators; in this
# package every .send is a socket or a socket-module helper.
_THREADY = ("thread", "proc", "worker", "streamer", "monitor")

# Method names owned by builtin collections/strings/files: never
# resolved through the unique-method-name fallback (typed attribute
# resolution may still reach a repo class method of the same name).
_BUILTIN_METHOD_NAMES = {
    "append", "appendleft", "add", "clear", "copy", "count", "discard",
    "extend", "get", "index", "insert", "items", "keys", "values",
    "pop", "popleft", "popitem", "put", "remove", "reverse", "sort",
    "setdefault", "update", "join", "split", "strip", "startswith",
    "endswith", "format", "encode", "decode", "read", "readline",
    "write", "writelines", "open", "close", "seek", "tell", "submit",
    "result", "cancel", "done", "get_nowait", "put_nowait", "qsize",
    "empty", "full", "isoformat", "lower", "upper", "replace",
}


def _expr_name(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


@dataclass
class LockDef:
    key: str               # canonical identity, e.g. "ParameterBuffer._lock"
    kind: str              # lock | rlock | condition | rwlock
    path: str
    lineno: int
    declared_name: Optional[str] = None   # make_lock("…") literal, if any


@dataclass
class AcqEvent:
    lock: str              # lock key (possibly unresolved "~Class.attr")
    lineno: int
    held: Tuple[str, ...]
    pragma: bool
    via: str               # "with" | "acquire" | "ctx"


@dataclass
class CallEvent:
    callee: Tuple          # ("self", m) | ("selfattr", a, m) |
                           # ("name", f) | ("attr", base, m)
    lineno: int
    held: Tuple[str, ...]
    pragma: bool


@dataclass
class BlockEvent:
    desc: str
    ident: str
    lineno: int
    held: Tuple[str, ...]
    pragma: bool
    receiver_lock: Optional[str] = None   # for .wait() on a known lock


@dataclass
class FuncInfo:
    module: str
    cls: Optional[str]
    name: str
    qual: str
    path: str
    acqs: List[AcqEvent] = field(default_factory=list)
    calls: List[CallEvent] = field(default_factory=list)
    blocks: List[BlockEvent] = field(default_factory=list)


@dataclass
class ClassInfo:
    name: str
    module: str
    path: str
    bases: List[str] = field(default_factory=list)
    lock_fields: Dict[str, LockDef] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, FuncInfo] = field(default_factory=dict)


def _module_short(rel: str) -> str:
    parts = Path(rel).with_suffix("").parts
    if parts and parts[0] == "elephas_tpu":
        parts = parts[1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else Path(rel).stem


def _lock_ctor_kind(expr: ast.expr) -> Optional[Tuple[str, Optional[str]]]:
    """``(kind, declared_name)`` if the expression constructs a lock.

    Handles ``threading.Lock()``, ``RWLock(...)``, the sanitizer's
    ``make_lock("…")`` factories, ``Condition(Lock())``, and either
    branch of an ``A() if c else B()`` conditional (the buffer's
    ``RWLock() if lock else NullLock()`` idiom).
    """
    if isinstance(expr, ast.IfExp):
        return _lock_ctor_kind(expr.body) or _lock_ctor_kind(expr.orelse)
    if not isinstance(expr, ast.Call):
        return None
    fn = expr.func
    name = None
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    if name in _THREADING_FACTORIES:
        # only threading.X / X — any receiver accepted (locksan alias)
        return _THREADING_FACTORIES[name], None
    if name in _LOCK_CLASS_CTORS:
        declared = None
        for kw in expr.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                declared = kw.value.value
        return _LOCK_CLASS_CTORS[name], declared
    if name in _SANITIZER_FACTORIES:
        declared = None
        if expr.args and isinstance(expr.args[0], ast.Constant) \
                and isinstance(expr.args[0].value, str):
            declared = expr.args[0].value
        return _SANITIZER_FACTORIES[name], declared
    return None


class _FileExtractor:
    """One pass over a module: lock definitions, attr types, and every
    function's acquisition/call/blocking events with lexical held
    stacks."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.module = _module_short(sf.rel)
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: List[FuncInfo] = []      # module-level + nested
        self.module_locks: Dict[str, LockDef] = {}
        self.imported_from: Dict[str, str] = {}  # name -> module
        self._extract()

    # -- helpers -------------------------------------------------------------

    def _pragma(self, lineno: int) -> bool:
        return LOCK_PRAGMA in self.sf.line(lineno)

    def _extract(self):
        tree = self.sf.tree
        for node in tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._record_import(node)
        # collect classes + their fields first (methods may `with` a
        # field assigned later in __init__)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._collect_class(node)
            elif isinstance(node, ast.Assign):
                self._collect_module_lock(node)
        # then walk bodies for events
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                ci = self.classes[node.name]
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fi = self._walk_function(item, ci, item.name)
                        ci.methods[item.name] = fi
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.append(
                    self._walk_function(node, None, node.name))

    def _record_import(self, node):
        if isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                self.imported_from[alias.asname or alias.name] = node.module
        elif isinstance(node, ast.Import):
            for alias in node.names:
                short = (alias.asname or alias.name).split(".")[0]
                self.imported_from[short] = alias.name

    def _collect_module_lock(self, node: ast.Assign):
        ctor = _lock_ctor_kind(node.value)
        if ctor is None:
            return
        kind, declared = ctor
        for t in node.targets:
            if isinstance(t, ast.Name):
                key = f"{self.module}.{t.id}"
                self.module_locks[t.id] = LockDef(
                    key, kind, self.sf.rel, node.lineno, declared)

    def _collect_class(self, node: ast.ClassDef):
        ci = ClassInfo(name=node.name, module=self.module, path=self.sf.rel,
                       bases=[b.id for b in node.bases
                              if isinstance(b, ast.Name)])
        self.classes[node.name] = ci
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign):
                continue
            for t in sub.targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                ctor = _lock_ctor_kind(sub.value)
                if ctor is not None:
                    kind, declared = ctor
                    key = f"{node.name}.{t.attr}"
                    ci.lock_fields[t.attr] = LockDef(
                        key, kind, self.sf.rel, sub.lineno, declared)
                    continue
                # plain constructor assignment -> attribute type
                v = sub.value
                if isinstance(v, ast.Call):
                    fn = v.func
                    ctor_name = None
                    if isinstance(fn, ast.Name):
                        ctor_name = fn.id
                    elif isinstance(fn, ast.Attribute):
                        ctor_name = fn.attr
                    if ctor_name and ctor_name[:1].isupper() \
                            and ctor_name not in _NULL_LOCK_CTORS:
                        ci.attr_types.setdefault(t.attr, ctor_name)

    # -- function walking ----------------------------------------------------

    def _walk_function(self, node, ci: Optional[ClassInfo],
                       qual: str) -> FuncInfo:
        fi = FuncInfo(module=self.module, cls=ci.name if ci else None,
                      name=node.name, qual=qual, path=self.sf.rel)
        local_locks: Dict[str, LockDef] = {}
        self._walk_stmts(node.body, (), fi, ci, local_locks, qual)
        return fi

    def _resolve_lock_expr(self, expr: ast.expr, ci: Optional[ClassInfo],
                           local_locks: Dict[str, LockDef]) -> Optional[str]:
        """Lock key for an expression naming a lock, else None."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            if ci is not None:
                ld = ci.lock_fields.get(expr.attr)
                if ld is not None:
                    return ld.key
                # unresolved self attr that LOOKS like a lock usage gets
                # a per-class placeholder (resolved against bases later)
                return f"~{ci.name}.{expr.attr}"
            return None
        if isinstance(expr, ast.Name):
            if expr.id in local_locks:
                return local_locks[expr.id].key
            if expr.id in self.module_locks:
                return self.module_locks[expr.id].key
        return None

    def _classify_withitem(self, item: ast.withitem, ci, local_locks
                           ) -> Optional[Tuple[str, str]]:
        """``(lock_key, via)`` if the context expr acquires a lock."""
        expr = item.context_expr
        # with self._lock: / with cond: / with local_lock:
        key = self._resolve_lock_expr(expr, ci, local_locks)
        if key is not None and not key.startswith("~"):
            return key, "with"
        # with self._lock.reading() / .writing():
        if isinstance(expr, ast.Call) and isinstance(expr.func,
                                                     ast.Attribute):
            if expr.func.attr in _CTX_ACQUIRE_METHODS | _ACQUIRE_METHODS:
                inner = self._resolve_lock_expr(expr.func.value, ci,
                                                local_locks)
                if inner is not None and not inner.startswith("~"):
                    return inner, "ctx"
        return None

    def _walk_stmts(self, stmts, held: Tuple[str, ...], fi: FuncInfo,
                    ci, local_locks, qual: str):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested function: its body runs later (thread targets,
                # callbacks) — own FuncInfo, empty held stack.
                nested = self._walk_function(st, ci, f"{qual}.{st.name}")
                self.functions.append(nested)
                continue
            if isinstance(st, ast.ClassDef):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in st.items:
                    got = self._classify_withitem(item, ci, local_locks)
                    # the context expression itself may contain calls
                    self._scan_expr(item.context_expr, new_held, fi, ci,
                                    local_locks, skip_lock_call=got
                                    is not None)
                    if got is not None:
                        lock, via = got
                        fi.acqs.append(AcqEvent(
                            lock, st.lineno, new_held,
                            self._pragma(st.lineno), via))
                        new_held = new_held + (lock,)
                self._walk_stmts(st.body, new_held, fi, ci, local_locks,
                                 qual)
                continue
            # local lock construction
            if isinstance(st, ast.Assign):
                ctor = _lock_ctor_kind(st.value)
                if ctor is not None:
                    kind, declared = ctor
                    for t in st.targets:
                        if isinstance(t, ast.Name):
                            key = f"{self.module}.{qual}.{t.id}"
                            local_locks[t.id] = LockDef(
                                key, kind, self.sf.rel, st.lineno, declared)
            # expressions of this statement
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.stmt):
                    continue
                if isinstance(child, ast.ExceptHandler):
                    continue
                self._scan_expr(child, held, fi, ci, local_locks)
            # nested statement lists (if/for/while/try bodies)
            for fname in ("body", "orelse", "finalbody"):
                sub = getattr(st, fname, None)
                if isinstance(sub, list) and sub \
                        and isinstance(sub[0], ast.stmt):
                    self._walk_stmts(sub, held, fi, ci, local_locks, qual)
            for handler in getattr(st, "handlers", []):
                self._walk_stmts(handler.body, held, fi, ci, local_locks,
                                 qual)

    def _scan_expr(self, expr, held, fi, ci, local_locks,
                   skip_lock_call: bool = False):
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                continue
            if not isinstance(node, ast.Call):
                continue
            self._classify_call(node, held, fi, ci, local_locks,
                                skip_lock_call=skip_lock_call
                                and node is expr)

    def _classify_call(self, node: ast.Call, held, fi: FuncInfo, ci,
                       local_locks, skip_lock_call: bool = False):
        fn = node.func
        pragma = self._pragma(node.lineno)
        # lock-method calls
        if isinstance(fn, ast.Attribute):
            recv_lock = self._resolve_lock_expr(fn.value, ci, local_locks)
            if recv_lock is not None and not recv_lock.startswith("~"):
                if fn.attr in _ACQUIRE_METHODS and not skip_lock_call:
                    # raw .acquire(): an ordering event; a NONBLOCKING
                    # try-acquire (blocking=False / 0) is deadlock-free
                    # by construction and adds no edge.
                    nonblocking = any(
                        isinstance(a, ast.Constant)
                        and a.value in (False, 0)
                        for a in node.args) or any(
                        kw.arg == "blocking"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value in (False, 0)
                        for kw in node.keywords)
                    if not nonblocking:
                        fi.acqs.append(AcqEvent(
                            recv_lock, node.lineno, held, pragma,
                            "acquire"))
                    return
                if fn.attr == "wait":
                    others = tuple(h for h in held if h != recv_lock)
                    if others:
                        fi.blocks.append(BlockEvent(
                            "condition wait while holding another lock",
                            f".wait() on {recv_lock}", node.lineno,
                            others, pragma, receiver_lock=recv_lock))
                    return
                if fn.attr in _LOCK_NOISE_METHODS \
                        or fn.attr in _CTX_ACQUIRE_METHODS:
                    return
            # blocking matchers ------------------------------------------
            base = _expr_name(fn.value) if isinstance(
                fn.value, (ast.Name, ast.Attribute)) else None
            # Blocking events are recorded even with an EMPTY held
            # stack: the direct rule only flags held ones, but a caller
            # holding a lock inherits the callee's blocking body
            # through the one-level interprocedural pass.
            if isinstance(fn.value, ast.Name) \
                    and (fn.value.id, fn.attr) in _BLOCK_MODULE_RECV:
                fi.blocks.append(BlockEvent(
                    _BLOCK_MODULE_RECV[(fn.value.id, fn.attr)],
                    f"{fn.value.id}.{fn.attr}", node.lineno, held,
                    pragma))
                return
            if fn.attr in _WIRE_NAMES:
                fi.blocks.append(BlockEvent(
                    "wire codec", f".{fn.attr}", node.lineno, held,
                    pragma))
                return
            if fn.attr in _BLOCK_ANY_RECV:
                fi.blocks.append(BlockEvent(
                    _BLOCK_ANY_RECV[fn.attr], f".{fn.attr}",
                    node.lineno, held, pragma))
                return
            if fn.attr == "send":
                fi.blocks.append(BlockEvent(
                    "socket send", ".send", node.lineno, held, pragma))
                return
            if fn.attr == "wait" and held:
                # wait() on a non-lock receiver (Event, Thread queue…)
                fi.blocks.append(BlockEvent(
                    "wait while holding a lock", ".wait", node.lineno,
                    held, pragma))
                return
            if fn.attr == "join" and held:
                rname = (_expr_name(fn.value) or "").lower()
                if any(t in rname for t in _THREADY):
                    fi.blocks.append(BlockEvent(
                        "thread join", f".{rname}.join", node.lineno,
                        held, pragma))
                    return
            # ordinary attribute call -> call event
            if isinstance(fn.value, ast.Attribute) \
                    and isinstance(fn.value.value, ast.Name) \
                    and fn.value.value.id == "self":
                fi.calls.append(CallEvent(
                    ("selfattr", fn.value.attr, fn.attr), node.lineno,
                    held, pragma))
            elif isinstance(fn.value, ast.Name) and fn.value.id == "self":
                fi.calls.append(CallEvent(
                    ("self", fn.attr), node.lineno, held, pragma))
            elif isinstance(fn.value, ast.Name):
                fi.calls.append(CallEvent(
                    ("attr", fn.value.id, fn.attr), node.lineno, held,
                    pragma))
            return
        if isinstance(fn, ast.Name):
            name = fn.id
            if name in _BLOCK_BARE_IMPORTED and name in self.imported_from:
                fi.blocks.append(BlockEvent(
                    _BLOCK_BARE_IMPORTED[name], name, node.lineno,
                    held, pragma))
                return
            fi.calls.append(CallEvent(("name", name), node.lineno, held,
                                      pragma))


# -- global analysis ---------------------------------------------------------


@dataclass
class LockEdge:
    src: str
    dst: str
    chain: Tuple[str, ...]
    lineno: int
    path: str
    pragma: bool


class LockAnalysis:
    """Whole-package extraction + graph. Built once, consumed by both
    lock rules and exported into ANALYSIS.json for the runtime
    sanitizer."""

    def __init__(self, repo: Repo, files: Sequence[SourceFile]):
        self.repo = repo
        self.extractors = [_FileExtractor(sf) for sf in files]
        self.classes: Dict[str, List[ClassInfo]] = {}
        self.lock_defs: Dict[str, LockDef] = {}
        self.methods_by_name: Dict[str, List[Tuple[ClassInfo, FuncInfo]]] = {}
        self.module_funcs: Dict[str, List[FuncInfo]] = {}
        self.all_funcs: List[FuncInfo] = []
        for ex in self.extractors:
            for ci in ex.classes.values():
                self.classes.setdefault(ci.name, []).append(ci)
                for ld in ci.lock_fields.values():
                    self.lock_defs[ld.key] = ld
                for m, fi in ci.methods.items():
                    self.methods_by_name.setdefault(m, []).append((ci, fi))
                    self.all_funcs.append(fi)
            for ld in ex.module_locks.values():
                self.lock_defs[ld.key] = ld
            for fi in ex.functions:
                self.module_funcs.setdefault(fi.name, []).append(fi)
                self.all_funcs.append(fi)
            # locals registered during walks
        self._edges: Optional[List[LockEdge]] = None
        self._suppressed_edges: List[LockEdge] = []
        self._eff_locks: Dict[int, Dict[str, Tuple[str, ...]]] = {}

    # -- callee resolution ---------------------------------------------------

    def _class_of(self, name: str) -> Optional[ClassInfo]:
        cands = self.classes.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def _method_on(self, ci: ClassInfo, m: str) -> Optional[FuncInfo]:
        seen = set()
        while ci is not None and ci.name not in seen:
            seen.add(ci.name)
            if m in ci.methods:
                return ci.methods[m]
            nxt = None
            for b in ci.bases:
                bc = self._class_of(b)
                if bc is not None:
                    nxt = bc
                    break
            ci = nxt
        return None

    def resolve_call(self, caller: FuncInfo, ev: CallEvent
                     ) -> Optional[FuncInfo]:
        kind = ev.callee[0]
        if kind == "self" and caller.cls:
            ci = self._class_of(caller.cls)
            if ci is not None:
                return self._method_on(ci, ev.callee[1])
            return None
        if kind == "selfattr" and caller.cls:
            attr, m = ev.callee[1], ev.callee[2]
            ci = self._class_of(caller.cls)
            if ci is not None:
                if attr in ci.lock_fields:
                    return None            # lock methods handled upstream
                tname = ci.attr_types.get(attr)
                if tname:
                    tc = self._class_of(tname)
                    if tc is not None:
                        return self._method_on(tc, m)
            return self._unique_method(m)
        if kind == "name":
            f = ev.callee[1]
            funcs = self.module_funcs.get(f, [])
            local = [fi for fi in funcs if fi.module == caller.module]
            if len(local) == 1:
                return local[0]
            if len(funcs) == 1:
                return funcs[0]
            return None
        if kind == "attr":
            base, m = ev.callee[1], ev.callee[2]
            funcs = self.module_funcs.get(m, [])
            based = [fi for fi in funcs
                     if fi.module == base or fi.module.endswith(f".{base}")]
            if len(based) == 1:
                return based[0]
            return self._unique_method(m)
        return None

    def _unique_method(self, m: str) -> Optional[FuncInfo]:
        """Global fallback: a method name defined by exactly ONE class
        package-wide resolves; anything ambiguous adds no edge. Names
        shared with builtin collections/files are excluded outright —
        ``self._events.append(...)`` is a list append, not a call into
        whatever repo class happens to define ``append``."""
        if m in _BUILTIN_METHOD_NAMES:
            return None
        cands = self.methods_by_name.get(m, [])
        if len(cands) == 1:
            return cands[0][1]
        return None

    # -- effective (transitive) lock acquisitions ---------------------------

    def eff_locks(self, fi: FuncInfo, _depth: int = 0,
                  _stack: Optional[Set[int]] = None
                  ) -> Dict[str, Tuple[str, ...]]:
        """``{lock_key: witness chain}`` of every lock this function may
        acquire, transitively through resolvable calls (depth-capped)."""
        key = id(fi)
        if key in self._eff_locks:
            return self._eff_locks[key]
        if _stack is None:
            _stack = set()
        if key in _stack or _depth > 6:
            return {}
        _stack.add(key)
        out: Dict[str, Tuple[str, ...]] = {}
        where = f"{fi.path}:{{ln}} {fi.cls + '.' if fi.cls else ''}{fi.qual}"
        for acq in fi.acqs:
            if acq.pragma:
                continue
            out.setdefault(
                acq.lock,
                (where.format(ln=acq.lineno) + f": acquires {acq.lock}",))
        for ev in fi.calls:
            if ev.pragma:
                continue
            callee = self.resolve_call(fi, ev)
            if callee is None or callee is fi:
                continue
            sub = self.eff_locks(callee, _depth + 1, _stack)
            step = (where.format(ln=ev.lineno)
                    + f": calls {callee.cls + '.' if callee.cls else ''}"
                      f"{callee.name}")
            for lock, chain in sub.items():
                if lock not in out and len(chain) < 6:
                    out[lock] = (step,) + chain
        _stack.discard(key)
        self._eff_locks[key] = out
        return out

    def direct_blocking(self, fi: FuncInfo) -> List[BlockEvent]:
        """Pragma-free blocking ops anywhere in the function body —
        what a caller holding a lock inherits (one level)."""
        return [b for b in fi.blocks if not b.pragma]

    # -- the graph -----------------------------------------------------------

    def edges(self) -> List[LockEdge]:
        if self._edges is not None:
            return self._edges
        found: Dict[Tuple[str, str], LockEdge] = {}
        suppressed: List[LockEdge] = []

        def add(src, dst, chain, lineno, path, pragma):
            e = LockEdge(src, dst, tuple(chain), lineno, path, pragma)
            if pragma:
                suppressed.append(e)
                return
            found.setdefault((src, dst), e)

        for fi in self.all_funcs:
            where = (f"{fi.path}:{{ln}} "
                     f"{fi.cls + '.' if fi.cls else ''}{fi.qual}")
            for acq in fi.acqs:
                for h in acq.held:
                    add(h, acq.lock,
                        [where.format(ln=acq.lineno)
                         + f": acquires {acq.lock} while holding {h}"],
                        acq.lineno, fi.path, acq.pragma)
            for ev in fi.calls:
                if not ev.held:
                    continue
                callee = self.resolve_call(fi, ev)
                if callee is None or callee is fi:
                    continue
                step = (where.format(ln=ev.lineno)
                        + f": calls {callee.cls + '.' if callee.cls else ''}"
                          f"{callee.name} while holding "
                        + ",".join(ev.held))
                for lock, chain in self.eff_locks(callee).items():
                    for h in ev.held:
                        add(h, lock, (step,) + chain, ev.lineno, fi.path,
                            ev.pragma)
        self._edges = sorted(found.values(), key=lambda e: (e.src, e.dst))
        self._suppressed_edges = suppressed
        return self._edges

    def suppressed_edges(self) -> List[LockEdge]:
        self.edges()
        return self._suppressed_edges

    def cycles(self) -> List[List[LockEdge]]:
        """Every elementary cycle reachable in the edge graph, as edge
        lists (self-edges included — a non-reentrant lock re-acquired
        through a call chain deadlocks on its own)."""
        edges = self.edges()
        adj: Dict[str, List[LockEdge]] = {}
        for e in edges:
            adj.setdefault(e.src, []).append(e)
        out: List[List[LockEdge]] = []
        seen_cycles: Set[Tuple[str, ...]] = set()

        for start in sorted(adj):
            # bounded DFS for cycles through `start`
            stack: List[Tuple[str, List[LockEdge]]] = [(start, [])]
            while stack:
                node, path = stack.pop()
                if len(path) > 8:
                    continue
                for e in adj.get(node, []):
                    if e.dst == start:
                        cyc = path + [e]
                        names = tuple(sorted(x.src for x in cyc))
                        if names not in seen_cycles:
                            seen_cycles.add(names)
                            out.append(cyc)
                    elif all(e.dst != p.src for p in path) \
                            and e.dst > start:
                        stack.append((e.dst, path + [e]))
        return out

    # -- export --------------------------------------------------------------

    def export(self) -> dict:
        edges = self.edges()
        return {
            "locks": [
                {"key": ld.key, "kind": ld.kind, "path": ld.path,
                 "lineno": ld.lineno}
                for ld in sorted(self.lock_defs.values(),
                                 key=lambda d: d.key)
            ],
            "edges": [
                {"src": e.src, "dst": e.dst, "path": e.path,
                 "lineno": e.lineno, "chain": list(e.chain)}
                for e in edges
            ],
        }


class LockOrderRule(Rule):
    name = "lock-order"
    pragma = LOCK_PRAGMA
    describe = ("package: the cross-module lock-acquisition graph must be "
                "acyclic (deadlock potential)")

    def __init__(self, analysis_for=None):
        self._analysis_for = analysis_for or get_analysis

    def scope(self, repo: Repo):
        return repo.package_files()

    def run(self, repo: Repo) -> List[Finding]:
        la = self._analysis_for(repo)
        out: List[Finding] = []
        for cyc in la.cycles():
            locks = [e.src for e in cyc]
            chain: List[str] = []
            for e in cyc:
                chain.extend(e.chain)
            first = cyc[0]
            if len(cyc) == 1 and first.src == first.dst:
                msg = (f"lock `{first.src}` re-acquired while already "
                       f"held (self-deadlock for a non-reentrant lock)")
            else:
                msg = ("lock-order cycle "
                       + " -> ".join(locks + [locks[0]])
                       + " (two threads entering from different nodes "
                         "deadlock)")
            out.append(Finding(
                rule=self.name, path=first.path, lineno=first.lineno,
                ident=" -> ".join(locks), line="", message=msg,
                chain=tuple(chain)))
        # make_lock("…") literals must match the derived identity
        for ld in la.lock_defs.values():
            if ld.declared_name is not None and ld.declared_name != ld.key:
                sf = repo.file(repo.root / ld.path)
                out.append(Finding(
                    rule=self.name, path=ld.path, lineno=ld.lineno,
                    ident=ld.key, line=sf.line(ld.lineno),
                    message=(f"sanitizer lock name {ld.declared_name!r} "
                             f"does not match the derived identity "
                             f"{ld.key!r} — the runtime sanitizer and "
                             f"the static graph must agree"),
                    suppressed=LOCK_PRAGMA in sf.line(ld.lineno)))
        # pragma'd edges are suppressions (dead-pragma audits them)
        for e in la.suppressed_edges():
            sf = repo.file(repo.root / e.path)
            out.append(Finding(
                rule=self.name, path=e.path, lineno=e.lineno,
                ident=f"{e.src}->{e.dst}", line=sf.line(e.lineno),
                message=f"edge {e.src} -> {e.dst} excluded by pragma",
                suppressed=True, chain=e.chain))
        return out


class BlockingUnderLockRule(Rule):
    name = "lock-blocking"
    pragma = LOCK_PRAGMA
    describe = ("package: no socket/fsync/flush/sleep/wire-codec/dispatch "
                "call while a lock is held")

    def __init__(self, analysis_for=None):
        self._analysis_for = analysis_for or get_analysis

    def scope(self, repo: Repo):
        return repo.package_files()

    def run(self, repo: Repo) -> List[Finding]:
        la = self._analysis_for(repo)
        out: List[Finding] = []
        for fi in la.all_funcs:
            for b in fi.blocks:
                if not b.held:
                    # a pragma here still does work: it stops the
                    # blocking body from propagating to callers that DO
                    # hold locks — record it so dead-pragma sees it live
                    if b.pragma:
                        sf = repo.file(repo.root / fi.path)
                        out.append(Finding(
                            rule=self.name, path=fi.path, lineno=b.lineno,
                            ident=b.ident, line=sf.line(b.lineno),
                            message=(f"{b.desc} (`{b.ident}`) sanctioned "
                                     f"— callers may hold locks across "
                                     f"this site"),
                            suppressed=True))
                    continue
                sf = repo.file(repo.root / fi.path)
                out.append(Finding(
                    rule=self.name, path=fi.path, lineno=b.lineno,
                    ident=b.ident, line=sf.line(b.lineno),
                    message=(f"{b.desc} (`{b.ident}`) while holding "
                             + ", ".join(b.held)
                             + " — blocking under a lock convoys every "
                               "contender"),
                    suppressed=b.pragma))
            # one-level interprocedural: call under lock -> callee blocks
            for ev in fi.calls:
                if not ev.held:
                    continue
                callee = la.resolve_call(fi, ev)
                if callee is None or callee is fi:
                    continue
                direct = la.direct_blocking(callee)
                if not direct:
                    continue
                b = direct[0]
                sf = repo.file(repo.root / fi.path)
                cname = (callee.cls + "." if callee.cls else "") + callee.name
                out.append(Finding(
                    rule=self.name, path=fi.path, lineno=ev.lineno,
                    ident=f"{cname}->{b.ident}", line=sf.line(ev.lineno),
                    message=(f"call to {cname} while holding "
                             + ", ".join(ev.held)
                             + f" reaches a blocking {b.desc} "
                               f"(`{b.ident}` at {callee.path}:{b.lineno})"),
                    suppressed=ev.pragma,
                    chain=(f"{callee.path}:{b.lineno}: {b.desc} "
                           f"`{b.ident}` in {cname}",)))
        return out


# -- per-repo analysis cache -------------------------------------------------

_CACHE: Dict[Path, LockAnalysis] = {}


def get_analysis(repo: Repo) -> LockAnalysis:
    la = _CACHE.get(repo.root)
    if la is None or la.repo is not repo:
        la = LockAnalysis(repo, repo.package_files())
        _CACHE[repo.root] = la
    return la
