"""The original ``scripts/lint_blocking.py`` rules, ported onto the
analysis subsystem.

The functional API (``lint_file``/``lint_package``/``lint_pickle_*``/
``lint_resilience_*``/``lint_metric_*``/``lint_kind_*``/
``lint_route_*``/``lint_pool_*`` + the vocab loaders and
:class:`Violation`) is preserved verbatim — ``scripts/lint_blocking.py``
is now a thin shim over this module and ``tests/test_lint_blocking.py``
exercises these implementations unchanged. What the port adds is the
suppression ledger: every scanner internally reports pragma-escaped
hits too, so the registry's dead-pragma rule can audit escapes, and the
:class:`~elephas_tpu.analysis.core.Rule` adapters at the bottom expose
each domain to the shared driver.

Rule semantics (unchanged — see each scanner's docstring):

1.  host-sync      — no blocking device→host conversions in serving/
                     outside ``host_sync.py`` (``# host-ok``)
2.  serving-clock  — no raw ``time.*()`` calls in serving/ (same pragma)
3.  ps-pickle      — no pickle outside ``parameter/wire.py``
                     (``# pickle-ok``)
4.  resilience-clock — no raw clock/sleep calls in resilience/
                     (``# clock-ok``)
5.  metric-naming  — counters end ``_total``, histograms ``_seconds``,
                     no f-string names (``# metric-ok``)
6.  kind-vocab     — flight kinds / alert rule names from the
                     registered tables (``# kind-ok``)
7.  route-vocab    — opsd routes from ``obs.opsd.ROUTES``
                     (``# route-ok``)
8.  pool-boundary  — no ``._cache``/``._pad`` reads outside
                     ``kv_pool.py`` (``# pool-ok``)
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, NamedTuple, Optional, Tuple

from elephas_tpu.analysis.core import Finding, Repo, Rule

PRAGMA = "host-ok"
SANCTIONED = "host_sync.py"
PICKLE_PRAGMA = "pickle-ok"
PICKLE_SANCTIONED = "wire.py"
CLOCK_PRAGMA = "clock-ok"
METRIC_PRAGMA = "metric-ok"
KIND_PRAGMA = "kind-ok"
ROUTE_PRAGMA = "route-ok"
POOL_PRAGMA = "pool-ok"
POOL_SANCTIONED = "kv_pool.py"
_POOL_PRIVATE = ("_cache", "_pad")
_NUMPY_NAMES = ("np", "numpy")
_CLOCK_ATTRS = ("time", "perf_counter", "monotonic")
_PICKLE_ATTRS = ("dumps", "loads", "dump", "load")
_METRIC_SUFFIX = {"counter": "_total", "histogram": "_seconds"}


class Violation(NamedTuple):
    path: str
    lineno: int
    call: str
    line: str
    domain: str = "serving"

    def __str__(self):
        if self.domain == "route":
            return (
                f"{self.path}:{self.lineno}: unregistered route "
                f"{self.call} — opsd routes come from obs.opsd.ROUTES "
                f"(grow the table so /meta, 404 bodies, and the fleet "
                f"poller stay in sync; `# {ROUTE_PRAGMA}` for test-local "
                f"throwaway routes)\n    {self.line.strip()}"
            )
        if self.domain == "kind":
            return (
                f"{self.path}:{self.lineno}: unregistered {self.call} — "
                f"FlightRecorder kinds come from obs.flight.KINDS and "
                f"alert rule names from obs.alerts.RULE_NAMES (grow the "
                f"table, never invent the string inline; `# {KIND_PRAGMA}` "
                f"for deliberate local vocab)\n    {self.line.strip()}"
            )
        if self.domain == "metric":
            return (
                f"{self.path}:{self.lineno}: metric name {self.call} "
                f"violates naming (counters end `_total`, histograms end "
                f"`_seconds`; an f-string name bakes a dimension into it — "
                f"use labelnames=; `# {METRIC_PRAGMA}` for deliberate "
                f"foreign names)\n    {self.line.strip()}"
            )
        if self.domain == "pool":
            return (
                f"{self.path}:{self.lineno}: donated-pool internal "
                f"{self.call} read outside kv_pool.py — donated buffers "
                f"must go through the guarded `pool.cache`/`pool.pad` "
                f"properties and `pool.swap()` (a raw `._cache` read can "
                f"hand out deleted buffers; `# {POOL_PRAGMA}` only for a "
                f"tree provably never donated)\n    {self.line.strip()}"
            )
        if self.domain == "resilience":
            what = "raw sleep" if self.call == "time.sleep" \
                else "raw clock call"
            return (
                f"{self.path}:{self.lineno}: {what} `{self.call}` in "
                f"resilience code bypasses the injected clock/sleep hooks "
                f"(thread a `clock=`/`sleep=` parameter so chaos tests run "
                f"on fake time; `# {CLOCK_PRAGMA}` only for timing outside "
                f"every detector/injector path)\n    {self.line.strip()}"
            )
        if self.call.startswith("pickle."):
            return (
                f"{self.path}:{self.lineno}: direct `{self.call}` outside "
                f"wire.py reintroduces per-request pickling on the PS hot "
                f"path (route through wire.encode_pickle/decode_pickle; "
                f"`# {PICKLE_PRAGMA}` only for data that never crosses the "
                f"wire)\n    {self.line.strip()}"
            )
        if self.call.startswith("time."):
            return (
                f"{self.path}:{self.lineno}: raw clock call `{self.call}` "
                f"bypasses the injected serving clock (read `self.clock()`; "
                f"`# {PRAGMA}` only for timing outside the scheduled path)"
                f"\n    {self.line.strip()}"
            )
        return (
            f"{self.path}:{self.lineno}: blocking host sync `{self.call}` "
            f"outside host_sync.py (add `# {PRAGMA}` only if the value "
            f"never touched the device)\n    {self.line.strip()}"
        )


# Internally every scanner returns (violation, suppressed) pairs; the
# public lint_* functions keep the historical unsuppressed-only shape.
_Scanned = List[Tuple[Violation, bool]]


def _unsuppressed(pairs: _Scanned) -> List[Violation]:
    return [v for v, suppressed in pairs if not suppressed]


def _call_name(node: ast.Call) -> str | None:
    """The lint-relevant name of a call, or None if it's not watched."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in ("int", "float"):
        return fn.id
    if isinstance(fn, ast.Attribute):
        if fn.attr in ("item", "tolist", "block_until_ready", "device_get"):
            return f".{fn.attr}" if fn.attr != "device_get" else "device_get"
        if fn.attr in ("asarray", "array") and isinstance(fn.value, ast.Name) \
                and fn.value.id in _NUMPY_NAMES:
            return f"{fn.value.id}.{fn.attr}"
        if fn.attr in _CLOCK_ATTRS and isinstance(fn.value, ast.Name) \
                and fn.value.id == "time":
            return f"time.{fn.attr}"
    return None


def _scan_serving(path: Path) -> _Scanned:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out: _Scanned = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name is None:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        out.append((Violation(str(path), node.lineno, name, line),
                    PRAGMA in line))
    return out


def lint_file(path: Path) -> List[Violation]:
    return _unsuppressed(_scan_serving(path))


def lint_package(root: Path) -> List[Violation]:
    """Lint every module in the serving package — recursively, so
    subpackages (``serving/fleet/``) inherit the blocking-read and
    clock-call bans — except the sanctioned sync point itself."""
    out = []
    for path in sorted(root.rglob("*.py")):
        if path.name == SANCTIONED:
            continue
        out.extend(lint_file(path))
    return out


def _pickle_call_name(node: ast.Call) -> str | None:
    """``pickle.dumps``-style attribute calls; bare ``loads(...)`` from a
    ``from pickle import loads`` is caught too (module-qualified name is
    synthesized so the message stays uniform)."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in _PICKLE_ATTRS \
            and isinstance(fn.value, ast.Name) \
            and fn.value.id in ("pickle", "cPickle"):
        return f"pickle.{fn.attr}"
    return None


def _scan_pickle(path: Path) -> _Scanned:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    imported = set()  # names bound by `from pickle import dumps as d`
    out: _Scanned = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "pickle":
            for alias in node.names:
                if alias.name in _PICKLE_ATTRS:
                    imported.add(alias.asname or alias.name)
        if not isinstance(node, ast.Call):
            continue
        name = _pickle_call_name(node)
        if name is None and isinstance(node.func, ast.Name) \
                and node.func.id in imported:
            name = f"pickle.{node.func.id}"
        if name is None:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        out.append((Violation(str(path), node.lineno, name, line),
                    PICKLE_PRAGMA in line))
    return out


def lint_pickle_file(path: Path) -> List[Violation]:
    return _unsuppressed(_scan_pickle(path))


def lint_pickle_package(root: Path) -> List[Violation]:
    """Lint every module in the parameter package except the sanctioned
    codec home itself."""
    out = []
    for path in sorted(root.glob("*.py")):
        if path.name == PICKLE_SANCTIONED:
            continue
        out.extend(lint_pickle_file(path))
    return out


def _resilience_call_name(node: ast.Call) -> str | None:
    """``time.<clock>()`` AND ``time.sleep()`` — the resilience domain
    bans both (everything there takes ``clock=``/``sleep=`` hooks)."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "time" \
            and fn.attr in _CLOCK_ATTRS + ("sleep",):
        return f"time.{fn.attr}"
    return None


def _scan_resilience(path: Path) -> _Scanned:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out: _Scanned = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _resilience_call_name(node)
        if name is None:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        out.append((Violation(str(path), node.lineno, name, line,
                              domain="resilience"), CLOCK_PRAGMA in line))
    return out


def lint_resilience_file(path: Path) -> List[Violation]:
    return _unsuppressed(_scan_resilience(path))


def lint_resilience_package(root: Path) -> List[Violation]:
    """Lint every module in the resilience package — no sanctioned file:
    real wall time enters ONLY through default-argument values."""
    out = []
    for path in sorted(root.glob("*.py")):
        out.extend(lint_resilience_file(path))
    return out


def _metric_call_name(node: ast.Call) -> str | None:
    """``<anything>.counter("…")`` / ``.histogram("…")`` with a judgeable
    first argument: a string literal that breaks the suffix convention,
    or any f-string (a baked dimension). Variable names pass — their
    literal is linted where it's defined."""
    fn = node.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _METRIC_SUFFIX
            and node.args):
        return None
    arg = node.args[0]
    if isinstance(arg, ast.JoinedStr):
        return f"<f-string> in .{fn.attr}()"
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
            and not arg.value.endswith(_METRIC_SUFFIX[fn.attr]):
        return f"`{arg.value}` in .{fn.attr}()"
    return None


def _scan_metric(path: Path) -> _Scanned:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out: _Scanned = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _metric_call_name(node)
        if name is None:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        out.append((Violation(str(path), node.lineno, name, line,
                              domain="metric"), METRIC_PRAGMA in line))
    return out


def lint_metric_file(path: Path) -> List[Violation]:
    return _unsuppressed(_scan_metric(path))


def lint_metric_package(root: Path) -> List[Violation]:
    """Lint EVERY module of the package tree — metric names are a
    process-global namespace, so no file is exempt."""
    out = []
    for path in sorted(root.rglob("*.py")):
        out.extend(lint_metric_file(path))
    return out


def load_registered_vocab(pkg_root: Path):
    """``(KINDS, RULE_NAMES)`` read straight from the defining modules'
    ASTs — pure-literal tuples by construction, so ``literal_eval``
    suffices and the lint never has to import the package (which would
    drag in jax)."""
    out = {}
    for fname, const in (("flight.py", "KINDS"), ("alerts.py", "RULE_NAMES")):
        tree = ast.parse((pkg_root / "obs" / fname).read_text())
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == const
                    for t in node.targets):
                out[const] = tuple(ast.literal_eval(node.value))
    return out["KINDS"], out["RULE_NAMES"]


def _kind_call_names(node: ast.Call, kinds, rule_names) -> List[str]:
    """Unregistered-vocabulary findings for one call. A positional
    string to ``.note(…)`` is uniquely a FlightRecorder kind (span
    ``note`` is kwargs-only); ``AlertRule(…)`` is judged on its name
    (first positional) and ``kind=`` keyword. Strings that arrive
    through variables pass — the literal is linted at its definition."""
    fn = node.func
    found = []

    def judge(arg, vocab, where):
        if isinstance(arg, ast.JoinedStr):
            found.append(f"<f-string> {where}")
        elif isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value not in vocab:
            found.append(f"`{arg.value}` {where}")

    if isinstance(fn, ast.Attribute) and fn.attr == "note" and node.args:
        judge(node.args[0], kinds, "kind in .note()")
    callee = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if callee == "AlertRule":
        if node.args:
            judge(node.args[0], rule_names, "rule name in AlertRule()")
        for kw in node.keywords:
            if kw.arg == "kind":
                judge(kw.value, kinds, "kind in AlertRule()")
    return found


def _scan_kind(path: Path, kinds, rule_names) -> _Scanned:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out: _Scanned = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        names = _kind_call_names(node, kinds, rule_names)
        if not names:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        for name in names:
            out.append((Violation(str(path), node.lineno, name, line,
                                  domain="kind"), KIND_PRAGMA in line))
    return out


def lint_kind_file(path: Path, kinds, rule_names) -> List[Violation]:
    return _unsuppressed(_scan_kind(path, kinds, rule_names))


def lint_kind_package(pkg_root: Path,
                      extra_roots: Tuple[Path, ...] = ()) -> List[Violation]:
    """Lint the whole package tree plus any extra roots (``scripts/``) —
    the vocabulary is process-global, so no file is exempt."""
    kinds, rule_names = load_registered_vocab(pkg_root)
    out = []
    paths = sorted(pkg_root.rglob("*.py"))
    for root in extra_roots:
        paths.extend(sorted(root.glob("*.py")))
    for path in paths:
        out.extend(lint_kind_file(path, kinds, rule_names))
    return out


def load_route_vocab(pkg_root: Path) -> Tuple[str, ...]:
    """``ROUTES`` read straight from ``obs/opsd.py``'s AST — a
    pure-literal tuple by construction, so ``literal_eval`` suffices and
    the lint never imports the package."""
    tree = ast.parse((pkg_root / "obs" / "opsd.py").read_text())
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "ROUTES"
                for t in node.targets):
            return tuple(ast.literal_eval(node.value))
    raise RuntimeError("obs/opsd.py has no literal ROUTES table")


def _route_call_names(node: ast.Call, routes) -> List[str]:
    """Unregistered-route findings for one call: a string literal (or
    f-string) as the first argument of ``add_route``/``_add_route``.
    Paths through variables pass — linted at the literal's definition."""
    fn = node.func
    callee = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if callee not in ("add_route", "_add_route") or not node.args:
        return []
    arg = node.args[0]
    if isinstance(arg, ast.JoinedStr):
        return [f"<f-string> in {callee}()"]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
            and arg.value not in routes:
        return [f"`{arg.value}` in {callee}()"]
    return []


def _scan_route(path: Path, routes) -> _Scanned:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out: _Scanned = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        names = _route_call_names(node, routes)
        if not names:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        for name in names:
            out.append((Violation(str(path), node.lineno, name, line,
                                  domain="route"), ROUTE_PRAGMA in line))
    return out


def lint_route_file(path: Path, routes) -> List[Violation]:
    return _unsuppressed(_scan_route(path, routes))


def lint_route_package(pkg_root: Path,
                       extra_roots: Tuple[Path, ...] = ()) -> List[Violation]:
    """Lint the whole package tree plus any extra roots (``scripts/``) —
    the route table is what every fleet poller keys on, so no file is
    exempt."""
    routes = load_route_vocab(pkg_root)
    out = []
    paths = sorted(pkg_root.rglob("*.py"))
    for root in extra_roots:
        paths.extend(sorted(root.glob("*.py")))
    for path in paths:
        out.extend(lint_route_file(path, routes))
    return out


def _scan_pool(path: Path) -> _Scanned:
    """Attribute READS of the pool's private donated leaves. Writes
    (``x._cache = …``) are equally foreign outside the pool, so any
    ``._cache`` / ``._pad`` attribute node is flagged regardless of
    load/store context — the distinction isn't worth the subtlety."""
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out: _Scanned = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Attribute)
                and node.attr in _POOL_PRIVATE):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        out.append((Violation(str(path), node.lineno, f"`.{node.attr}`",
                              line, domain="pool"), POOL_PRAGMA in line))
    return out


def lint_pool_file(path: Path) -> List[Violation]:
    return _unsuppressed(_scan_pool(path))


def lint_pool_package(root: Path) -> List[Violation]:
    """Lint the serving package tree except the pool module itself —
    the only file allowed to touch the donated leaves directly."""
    out = []
    for path in sorted(root.rglob("*.py")):
        if path.name == POOL_SANCTIONED:
            continue
        out.extend(lint_pool_file(path))
    return out


def main(argv: List[str] | None = None,
         repo_root: Optional[Path] = None) -> List[Violation]:
    """Historical CLI: serving lint by default; with no args, every
    legacy domain. (``python -m elephas_tpu.analysis`` is the full
    driver — this stays for the ``lint_blocking.py`` shim.)"""
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    if repo_root is None:
        repo_root = Path(__file__).resolve().parents[2]
    pkg_root = repo_root / "elephas_tpu"
    scripts_root = repo_root / "scripts"
    root = Path(args[0]) if args else (pkg_root / "serving")
    violations = lint_package(root)
    if not args:
        violations.extend(lint_pool_package(pkg_root / "serving"))
        violations.extend(lint_pickle_package(pkg_root / "parameter"))
        violations.extend(lint_resilience_package(pkg_root / "resilience"))
        violations.extend(lint_metric_package(pkg_root))
        violations.extend(lint_kind_package(
            pkg_root, extra_roots=(scripts_root,)))
        violations.extend(lint_route_package(
            pkg_root, extra_roots=(scripts_root,)))
    for v in violations:
        print(v)
    if not violations:
        print(f"lint_blocking: {root} clean")
    return violations


# -- Rule adapters: the legacy domains on the shared registry ----------------


def _domain_of(v: Violation) -> str:
    if v.domain != "serving":
        return v.domain
    return "serving-clock" if v.call.startswith("time.") else "host-sync"


class _LegacyRule(Rule):
    """Adapter base: runs one legacy scanner over its historical scope
    and converts (Violation, suppressed) pairs to Findings."""

    def _convert(self, repo: Repo, pairs: _Scanned) -> List[Finding]:
        out = []
        for v, suppressed in pairs:
            try:
                rel = str(Path(v.path).relative_to(repo.root))
            except ValueError:
                rel = v.path
            out.append(Finding(
                rule=self.name, path=rel, lineno=v.lineno, ident=v.call,
                line=v.line, message=str(v).split("\n")[0].split(": ", 1)[1],
                suppressed=suppressed,
            ))
        return out


class HostSyncRule(_LegacyRule):
    name = "host-sync"
    pragma = PRAGMA
    describe = ("serving/: no blocking device->host conversion outside "
                "host_sync.py")

    def scope(self, repo: Repo):
        return repo.walk(repo.pkg / "serving", exclude=(SANCTIONED,))

    def run(self, repo: Repo) -> List[Finding]:
        out = []
        for sf in self.scope(repo):
            pairs = [(v, s) for v, s in _scan_serving(sf.path)
                     if not v.call.startswith("time.")]
            out.extend(self._convert(repo, pairs))
        return out


class ServingClockRule(_LegacyRule):
    name = "serving-clock"
    pragma = PRAGMA
    describe = "serving/: read the injected clock, never raw time.*()"

    def scope(self, repo: Repo):
        return repo.walk(repo.pkg / "serving", exclude=(SANCTIONED,))

    def run(self, repo: Repo) -> List[Finding]:
        out = []
        for sf in self.scope(repo):
            pairs = [(v, s) for v, s in _scan_serving(sf.path)
                     if v.call.startswith("time.")]
            out.extend(self._convert(repo, pairs))
        return out


class PicklePathRule(_LegacyRule):
    name = "ps-pickle"
    pragma = PICKLE_PRAGMA
    describe = "parameter/: pickle only inside wire.py"

    def scope(self, repo: Repo):
        return repo.walk(repo.pkg / "parameter", recursive=False,
                         exclude=(PICKLE_SANCTIONED,))

    def run(self, repo: Repo) -> List[Finding]:
        out = []
        for sf in self.scope(repo):
            out.extend(self._convert(repo, _scan_pickle(sf.path)))
        return out


class ResilienceClockRule(_LegacyRule):
    name = "resilience-clock"
    pragma = CLOCK_PRAGMA
    describe = "resilience/: injected clock=/sleep= hooks only"

    def scope(self, repo: Repo):
        return repo.walk(repo.pkg / "resilience", recursive=False)

    def run(self, repo: Repo) -> List[Finding]:
        out = []
        for sf in self.scope(repo):
            out.extend(self._convert(repo, _scan_resilience(sf.path)))
        return out


class MetricNamingRule(_LegacyRule):
    name = "metric-naming"
    pragma = METRIC_PRAGMA
    describe = "package: counters end _total, histograms _seconds, no f-names"

    def scope(self, repo: Repo):
        return repo.package_files()

    def run(self, repo: Repo) -> List[Finding]:
        out = []
        for sf in self.scope(repo):
            out.extend(self._convert(repo, _scan_metric(sf.path)))
        return out


class KindVocabRule(_LegacyRule):
    name = "kind-vocab"
    pragma = KIND_PRAGMA
    describe = "package+scripts: flight kinds / alert names from the tables"

    def scope(self, repo: Repo):
        return repo.package_files() + repo.scripts_files()

    def run(self, repo: Repo) -> List[Finding]:
        try:
            kinds, rule_names = load_registered_vocab(repo.pkg)
        except (FileNotFoundError, KeyError):
            return []          # synthetic repos without obs/ vocab tables
        out = []
        for sf in self.scope(repo):
            out.extend(self._convert(
                repo, _scan_kind(sf.path, kinds, rule_names)))
        return out


class RouteVocabRule(_LegacyRule):
    name = "route-vocab"
    pragma = ROUTE_PRAGMA
    describe = "package+scripts: opsd routes from obs.opsd.ROUTES"

    def scope(self, repo: Repo):
        return repo.package_files() + repo.scripts_files()

    def run(self, repo: Repo) -> List[Finding]:
        try:
            routes = load_route_vocab(repo.pkg)
        except (FileNotFoundError, RuntimeError):
            return []
        out = []
        for sf in self.scope(repo):
            out.extend(self._convert(repo, _scan_route(sf.path, routes)))
        return out


class PoolBoundaryRule(_LegacyRule):
    name = "pool-boundary"
    pragma = POOL_PRAGMA
    describe = "serving/: donated ._cache/._pad stay behind kv_pool.py"

    def scope(self, repo: Repo):
        return repo.walk(repo.pkg / "serving", exclude=(POOL_SANCTIONED,))

    def run(self, repo: Repo) -> List[Finding]:
        out = []
        for sf in self.scope(repo):
            out.extend(self._convert(repo, _scan_pool(sf.path)))
        return out


LEGACY_RULES = (
    HostSyncRule(),
    ServingClockRule(),
    PicklePathRule(),
    ResilienceClockRule(),
    MetricNamingRule(),
    KindVocabRule(),
    RouteVocabRule(),
    PoolBoundaryRule(),
)
