"""Dead-pragma audit: an escape comment must still be escaping something.

Every ``# host-ok`` / ``# clock-ok`` / ``# lock-ok`` … pragma was
written to silence a specific finding on that line. Code drifts: the
offending call gets refactored away, the line gets split, the rule gets
smarter — and the pragma stays behind, silently pre-authorizing
whatever lands on that line next. That rot is exactly the failure mode
escape hatches are criticized for, so this rule closes it: a pragma on
a line where no rule honoring that pragma reports a (suppressed)
finding is itself a violation.

Mechanics: the other rules report their pragma-escaped hits as
``suppressed=True`` findings; this rule tokenizes each in-scope file
for REAL comment pragmas (string-literal mentions — e.g. a lint message
template naming its own pragma — are invisible, see
:meth:`SourceFile.comment_pragmas`) and cross-references. A pragma only
counts as live if a suppressed finding from a rule honoring it sits on
the same line of the same file. Scope: a pragma is only audited in
files some honoring rule actually scans — a ``# host-ok`` in a test
file no rule reads is commentary, not an escape.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from elephas_tpu.analysis.core import Finding, Repo, Rule


class DeadPragmaRule(Rule):
    name = "dead-pragma"
    pragma = ""          # not escapable — delete the pragma instead
    describe = ("package: every escape pragma must still suppress a "
                "finding on its line (no silent rot)")

    def __init__(self, rules: Sequence[Rule]):
        #: the rules whose suppressions this audit cross-references
        self.rules = [r for r in rules if r.pragma]

    def scope(self, repo: Repo):
        seen = {}
        for r in self.rules:
            for sf in r.scope(repo):
                seen[sf.path] = sf
        return [seen[k] for k in sorted(seen)]

    def run(self, repo: Repo,
            findings: Optional[Iterable[Finding]] = None) -> List[Finding]:
        """``findings``: pre-computed output of ``self.rules`` (the CLI
        runs each rule once and shares); when omitted the rules run
        here — same result, twice the AST walks."""
        if findings is None:
            findings = [f for r in self.rules for f in r.run(repo)]
        pragma_rules: Dict[str, List[Rule]] = {}
        for r in self.rules:
            pragma_rules.setdefault(r.pragma, []).append(r)
        rule_pragma = {r.name: r.pragma for r in self.rules}
        # (path, lineno, pragma) triples where a suppression proves the
        # pragma live
        live: Set[Tuple[str, int, str]] = set()
        for f in findings:
            if f.suppressed and f.rule in rule_pragma:
                live.add((f.path, f.lineno, rule_pragma[f.rule]))
        # which files each pragma is audited in
        pragma_scope: Dict[str, Set[str]] = {}
        for pragma, rules in pragma_rules.items():
            scoped: Set[str] = set()
            for r in rules:
                scoped.update(sf.rel for sf in r.scope(repo))
            pragma_scope[pragma] = scoped
        out: List[Finding] = []
        for sf in self.scope(repo):
            for lineno, pragmas in sorted(sf.comment_pragmas().items()):
                for pragma in pragmas:
                    if pragma not in pragma_rules:
                        continue
                    if sf.rel not in pragma_scope[pragma]:
                        continue
                    if (sf.rel, lineno, pragma) in live:
                        continue
                    rules = ", ".join(r.name
                                      for r in pragma_rules[pragma])
                    out.append(Finding(
                        rule=self.name, path=sf.rel, lineno=lineno,
                        ident=pragma, line=sf.line(lineno),
                        message=(f"dead pragma `# {pragma}` — no rule "
                                 f"({rules}) reports anything on this "
                                 f"line; delete it or re-justify it"),
                    ))
        return out
