"""Static-analysis subsystem: rule registry over a parse-once repo view.

Grown out of ``scripts/lint_blocking.py`` (now a shim): the eight
legacy lint domains plus the concurrency analyzers (``lock-order``,
``lock-blocking``) and the ``dead-pragma`` audit. Run with
``python -m elephas_tpu.analysis``; see ``--list-rules``.
"""

from elephas_tpu.analysis.core import (PRAGMAS, Finding, Repo, Rule,
                                       SourceFile, suppressions, violations)
from elephas_tpu.analysis.cli import (build_report, build_rules, main,
                                      run_rules)
from elephas_tpu.analysis.locks import (BlockingUnderLockRule, LockAnalysis,
                                        LockOrderRule, get_analysis)
from elephas_tpu.analysis.pragmas import DeadPragmaRule

__all__ = [
    "PRAGMAS", "Finding", "Repo", "Rule", "SourceFile",
    "suppressions", "violations",
    "build_report", "build_rules", "main", "run_rules",
    "BlockingUnderLockRule", "LockAnalysis", "LockOrderRule",
    "get_analysis", "DeadPragmaRule",
]
