"""Elastic execution: a work-stealing unit ledger + self-healing pool.

The reference's elasticity WAS Spark: a lost executor's partitions were
re-run by the scheduler, job-wide counters stayed exact, and a new
executor could join mid-stage (SURVEY.md §5.3). This module rebuilds
exactly that contract for the thread-per-chip async trainer:

- ``UnitLedger`` is the scheduler's task table: every frequency unit is
  ``(epoch, partition)`` — or, with ``batches_per_unit`` set,
  ``(epoch, partition, (lo, hi))`` batch ranges so a death mid-epoch
  re-leases only the unfinished ranges — leased epoch-major to
  whichever worker asks next. A dead worker's leases go back to the
  FRONT of the queue
  (earliest epochs first), and **each unit counts exactly once** — a
  zombie (a stalled worker that wakes after its lease was revoked and
  finished by a survivor) can deliver a duplicate completion and the
  ledger ignores it, so total frequency-unit accounting stays exact
  under any interleaving of deaths, stalls, and rejoins.
- ``ElasticWorkerPool`` owns the worker threads: it heartbeats each
  worker through its PS client at every unit boundary, polls the PS
  membership table (``resilience.liveness``) so a STALLED worker (one
  that cannot raise) is detector-expired and its units re-queued to
  survivors, fences revived zombies (a worker that sees itself declared
  dead exits instead of double-completing), admits late joiners
  mid-fit (``join_worker`` — they pull a fresh snapshot via their
  client like any other unit), and survives a parameter-server restart:
  ``ParameterServerUnavailable`` — fail-fast and fatal at the WIRE
  layer, which is the contract PR 4 pinned — is caught HERE, the unit
  is re-queued, and the worker polls ``client.health()`` under a
  bounded ``ps_recovery_grace`` budget for the warm-restarted server
  before resuming with a fresh client. Policy lives in the resilience
  layer; the wire client stays fail-fast.

Observability: ``resilience/mttr_seconds`` (gauge — seconds from a
failure to the first re-queued unit completing, the per-event MTTR),
``resilience/requeue`` + ``resilience/ps_reconnect`` spans, and a
``stats`` dict (deaths, re-queues, outages, MTTR samples) returned by
``wait()`` and surfaced in the trainer history.

Clock discipline: all time flows through injected ``clock``/``sleep``
(enforced by ``scripts/lint_blocking.py``) so chaos tests replay on a
fake clock without real waits where possible.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from elephas_tpu import obs
from elephas_tpu.parameter.client import ParameterServerUnavailable
from elephas_tpu.resilience.faults import FaultInjector, InjectedWorkerDeath
from elephas_tpu.resilience.liveness import MembershipView

#: A ledger unit is ``(epoch, partition)`` (whole-partition granularity)
#: or ``(epoch, partition, (lo, hi))`` (a half-open batch range) — the
#: first two slots are stable either way, so span tags and the pool's
#: per-(epoch, partition) metric table index units identically.
Unit = Tuple


class UnitLedger:
    """Exactly-once accounting over the fit's frequency units.

    Thread-safe. Leases hand out pending units epoch-major (all of
    epoch e before any of e+1 — re-queued units from a death go back to
    the front in epoch order, so survivors repair the earliest hole
    first). ``complete`` is idempotent per unit: the first completion
    counts, anything later (zombie double-completion) is ignored.

    Granularity: by default one unit per ``(epoch, partition)``. With
    ``n_batches`` (per-partition batch counts) the ledger re-keys on
    ``(epoch, partition, (lo, hi))`` batch ranges of ``batches_per_unit``
    batches each (last range may be short) — so a death mid-epoch
    re-leases only the UNFINISHED ranges to survivors instead of
    re-running whole partitions. Epoch completion is counted against the
    true per-epoch unit count (``units_per_epoch``), never against
    ``len(partitions)`` — range completions arrive out of order across
    partitions and an epoch is done only when every range of every
    partition has counted exactly once.
    """

    def __init__(self, epochs: int, partitions: List[int],
                 n_batches=None, batches_per_unit: Optional[int] = None):
        if epochs < 1 or not partitions:
            raise ValueError(
                f"need >=1 epoch and >=1 partition, got {epochs}/{partitions}"
            )
        self.epochs = epochs
        self.partitions = list(partitions)
        if n_batches is None:
            if batches_per_unit is not None:
                raise ValueError(
                    "batches_per_unit needs n_batches (per-partition "
                    "batch counts) to cut ranges from"
                )
            self.ranges: Optional[Dict[int, List[Tuple[int, int]]]] = None
            units = [(e, p) for e in range(epochs) for p in self.partitions]
            self.units_per_epoch = len(self.partitions)
        else:
            if isinstance(n_batches, int):
                n_batches = {p: n_batches for p in self.partitions}
            ranges: Dict[int, List[Tuple[int, int]]] = {}
            for p in self.partitions:
                nb = int(n_batches[p])
                if nb < 1:
                    raise ValueError(
                        f"partition {p}: need >=1 batch, got {nb}"
                    )
                step = nb if batches_per_unit is None \
                    else max(1, int(batches_per_unit))
                ranges[p] = [
                    (lo, min(lo + step, nb)) for lo in range(0, nb, step)
                ]
            self.ranges = ranges
            self.units_per_epoch = sum(len(r) for r in ranges.values())
            units = [
                (e, p, r)
                for e in range(epochs)
                for p in self.partitions
                for r in ranges[p]
            ]
        self.batches_per_unit = batches_per_unit
        self._pending: deque = deque(units)
        self._leased: Dict[Unit, str] = {}
        self._done: Dict[Unit, str] = {}
        self._epoch_done: List[int] = [0] * epochs
        self._requeued_total = 0
        self._lock = threading.Lock()

    @property
    def total_units(self) -> int:
        return self.epochs * self.units_per_epoch

    @property
    def completed_units(self) -> int:
        with self._lock:
            return len(self._done)

    @property
    def requeued_units(self) -> int:
        with self._lock:
            return self._requeued_total

    def lease(self, worker_id: str) -> Optional[Unit]:
        """Next pending unit, or None (nothing pending right now — the
        caller should re-check ``all_done`` and idle-wait: other
        workers' leases may yet be re-queued)."""
        with self._lock:
            if not self._pending:
                return None
            unit = self._pending.popleft()
            self._leased[unit] = str(worker_id)
            return unit

    def complete(self, worker_id: str, unit: Unit) -> Tuple[bool, Optional[int]]:
        """Record a completion. Returns ``(counted, finished_epoch)``:
        ``counted`` is False for duplicates (revoked lease completed by
        a zombie after a survivor already delivered it); and when this
        completion finishes its whole epoch, ``finished_epoch`` is that
        epoch number (fire validation/callbacks once per epoch)."""
        with self._lock:
            if unit in self._done:
                return False, None
            self._done[unit] = str(worker_id)
            self._leased.pop(unit, None)
            # A zombie can complete a unit that was re-queued and is
            # sitting in pending again — drop the duplicate copy so no
            # survivor re-runs already-counted work.
            try:
                self._pending.remove(unit)
            except ValueError:
                pass
            epoch = unit[0]
            self._epoch_done[epoch] += 1
            # Compare against the TRUE per-epoch unit count: under
            # batch-range keying there are more units per epoch than
            # partitions, and completions land out of order — counting
            # against len(partitions) would fire the epoch early.
            finished = epoch if self._epoch_done[epoch] == self.units_per_epoch \
                else None
            return True, finished

    def add_units(self, units: List[Unit]) -> int:
        """Grow the ledger mid-drain (the tuner's rung promotions: a
        promoted trial's next rung becomes schedulable work the moment
        the promoting result lands). Appended to the BACK of the queue
        — promotions are new work, not repair — and deduped against
        pending/leased/done so an idempotent caller (a zombie replaying
        a promotion decision) cannot double-schedule a unit. Epochs
        beyond the constructed range extend the per-epoch table; such
        late epochs never fire ``finished_epoch`` (their population is
        dynamic, there is no "all partitions" to count against — the
        scheduler owns completion semantics for dynamic work). Returns
        how many units were actually added.

        Safe-growth contract: callers must add units from INSIDE the
        processing of a still-leased unit (or before ``start``), so
        ``all_done`` can never report True while a grow is in flight.
        """
        added = 0
        with self._lock:
            pending = set(self._pending)
            for unit in units:
                unit = tuple(unit)
                if unit in self._done or unit in self._leased \
                        or unit in pending:
                    continue
                epoch = int(unit[0])
                while epoch >= len(self._epoch_done):
                    self._epoch_done.append(0)
                    # keep units_per_epoch as the rung-0 population;
                    # dynamic epochs opt out of epoch-complete firing
                    # by construction (population unknown).
                self.epochs = max(self.epochs, epoch + 1)
                self._pending.append(unit)
                pending.add(unit)
                added += 1
        return added

    def requeue_worker(self, worker_id: str) -> List[Unit]:
        """Return all of ``worker_id``'s leases to the FRONT of the
        queue (epoch-major order preserved); idempotent."""
        worker_id = str(worker_id)
        with self._lock:
            units = sorted(
                u for u, w in self._leased.items() if w == worker_id
            )
            for unit in reversed(units):
                self._leased.pop(unit, None)
                self._pending.appendleft(unit)
            self._requeued_total += len(units)
            return units

    def all_done(self) -> bool:
        with self._lock:
            return not self._pending and not self._leased

    def outstanding(self) -> Dict[str, int]:
        with self._lock:
            return {
                "pending": len(self._pending),
                "leased": len(self._leased),
                "done": len(self._done),
            }

    def epoch_complete(self, epoch: int) -> bool:
        with self._lock:
            return self._epoch_done[epoch] == self.units_per_epoch


class _WorkerCtx:
    __slots__ = ("worker_id", "unit_seq", "thread")

    def __init__(self, worker_id: str):
        self.worker_id = worker_id
        self.unit_seq = 0  # leased-unit counter: the fault plan's step index
        self.thread: Optional[threading.Thread] = None


class ElasticWorkerPool:
    """Self-healing thread pool draining a ``UnitLedger``.

    ``run_unit(worker_id, client, unit) -> metrics`` is the trainer's
    workload (pull → train one frequency unit → push); the pool owns
    scheduling, heartbeats, death handling, PS-restart recovery, and
    late joins. ``client_factory(worker_id)`` must return a parameter
    client exposing ``heartbeat``/``membership``/``health`` (all three
    transports do).
    """

    def __init__(
        self,
        ledger: UnitLedger,
        run_unit: Callable,
        client_factory: Callable,
        worker_ids: List[str],
        on_epoch_complete: Optional[Callable] = None,
        injector: Optional[FaultInjector] = None,
        ps_recovery_grace: float = 15.0,
        monitor_poll: float = 0.1,
        idle_wait: float = 0.02,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.ledger = ledger
        self.run_unit = run_unit
        self.client_factory = client_factory
        self.on_epoch_complete = on_epoch_complete
        self.injector = injector
        self.ps_recovery_grace = float(ps_recovery_grace)
        self.monitor_poll = float(monitor_poll)
        self.idle_wait = float(idle_wait)
        self._clock = clock
        self._sleep = sleep
        self.membership = MembershipView()
        self.stats: Dict = {
            "worker_deaths": [],
            "ps_outages": [],
            "mttr_samples": [],
            "late_joins": [],
            "fenced": [],
        }
        self._mttr_gauge = obs.default_registry().gauge(
            "resilience/mttr_seconds",
            help="seconds from a failure to the first re-queued unit completing",
        )
        self._tracer = obs.default_tracer()
        self._lock = threading.Lock()
        self._ctxs: Dict[str, _WorkerCtx] = {}
        self._fatal: Optional[BaseException] = None
        self._stop = False
        self._fire_lock = threading.Lock()
        # Units awaiting repair: unit -> failure timestamp. The first
        # counted completion of such a unit closes the MTTR window.
        self._repairing: Dict[Unit, float] = {}
        self._epoch_metrics: Dict[int, Dict[int, Dict]] = {}
        # How many units have folded into each (epoch, partition) metric
        # slot — batch-range units running-mean into one row so the
        # epoch_metrics() shape is granularity-independent.
        self._metric_counts: Dict[Tuple[int, int], int] = {}
        self._monitor_thread: Optional[threading.Thread] = None
        for worker_id in worker_ids:
            self._ctxs[str(worker_id)] = _WorkerCtx(str(worker_id))

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        for ctx in self._ctxs.values():
            self._start_worker(ctx)
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, daemon=True, name="elastic-monitor"
        )
        self._monitor_thread.start()

    def _start_worker(self, ctx: _WorkerCtx) -> None:
        ctx.thread = threading.Thread(
            target=self._worker_loop, args=(ctx,), daemon=True,
            name=f"elastic-worker-{ctx.worker_id}",
        )
        ctx.thread.start()

    def join_worker(self, worker_id: str) -> None:
        """Admit a late joiner mid-fit: it leases from the ledger like
        any survivor, and its first ``run_unit`` pulls a fresh snapshot
        through its own client (version-gated pull: a new client holds
        no cached version, so it always receives a full body)."""
        worker_id = str(worker_id)
        with self._lock:
            if worker_id in self._ctxs and self._ctxs[worker_id].thread is not None \
                    and self._ctxs[worker_id].thread.is_alive():
                raise ValueError(f"worker {worker_id} is already in the pool")
            ctx = _WorkerCtx(worker_id)
            self._ctxs[worker_id] = ctx
            self.stats["late_joins"].append(worker_id)
        self._start_worker(ctx)

    def wait(self) -> Dict:
        """Block until the ledger drains (or the pool dies); returns
        ``stats``. Raises the recorded fatal (PS unrecoverable, or every
        worker dead with work still pending)."""
        while True:
            with self._lock:
                threads = [c.thread for c in self._ctxs.values() if c.thread]
            alive = [t for t in threads if t.is_alive()]
            if not alive:
                break
            for t in alive:
                t.join(timeout=0.2)
        self._stop = True
        if self._monitor_thread is not None:
            self._monitor_thread.join()
        if self._fatal is not None:
            raise self._fatal
        if not self.ledger.all_done():
            raise RuntimeError(
                "elastic pool exhausted its workers with units still "
                f"outstanding: {self.ledger.outstanding()} "
                f"(deaths: {self.stats['worker_deaths']})"
            )
        self.stats["requeued_units"] = self.ledger.requeued_units
        self.stats["completed_units"] = self.ledger.completed_units
        return self.stats

    def epoch_metrics(self) -> Dict[int, Dict[int, Dict]]:
        with self._lock:
            return {e: dict(parts) for e, parts in self._epoch_metrics.items()}

    # -- internals -------------------------------------------------------

    def _beat(self, client, worker_id: str) -> None:
        try:
            client.heartbeat(worker_id)
        except ParameterServerUnavailable:
            raise
        except Exception:
            pass  # heartbeat is advisory; the detector tolerates gaps

    def _record_death(self, worker_id: str, reason: str, units: List[Unit]) -> None:
        now = self._clock()
        with self._lock:
            for unit in units:
                self._repairing.setdefault(unit, now)
            self.stats["worker_deaths"].append(
                {"worker": worker_id, "reason": reason,
                 "requeued_units": list(units)}
            )
        with self._tracer.span("resilience/requeue", worker=worker_id,
                               units=len(units), reason=reason):
            pass

    def _note_repaired(self, unit: Unit) -> None:
        with self._lock:
            failed_at = self._repairing.pop(unit, None)
        if failed_at is not None:
            mttr = self._clock() - failed_at
            self._mttr_gauge.set(mttr)
            with self._lock:
                self.stats["mttr_samples"].append(mttr)

    def _record_ps_outage(self, worker_id: str, detected: float,
                          recovered: Optional[float]) -> None:
        with self._lock:
            self.stats["ps_outages"].append({
                "worker": worker_id,
                "outage_s": None if recovered is None else recovered - detected,
                "recovered": recovered is not None,
            })
        if recovered is not None:
            self._mttr_gauge.set(recovered - detected)
            with self._lock:
                self.stats["mttr_samples"].append(recovered - detected)

    def _await_ps(self, worker_id: str, old_client):
        """Poll for a warm-restarted PS under the grace budget; returns
        a FRESH client (the old one may hold poisoned state) or None."""
        detected = self._clock()
        if hasattr(old_client, "close"):
            try:
                old_client.close()
            except Exception:
                pass
        with self._tracer.span("resilience/ps_reconnect", worker=worker_id):
            deadline = detected + self.ps_recovery_grace
            while not self._stop and self._clock() < deadline:
                try:
                    client = self.client_factory(worker_id)
                    if client.health():
                        self._record_ps_outage(worker_id, detected, self._clock())
                        return client
                    if hasattr(client, "close"):
                        client.close()
                except Exception:
                    pass
                self._sleep(min(0.1, self.ps_recovery_grace / 10.0))
        self._record_ps_outage(worker_id, detected, None)
        return None

    def _worker_loop(self, ctx: _WorkerCtx) -> None:
        worker_id = ctx.worker_id
        client = None
        try:
            client = self.client_factory(worker_id)
            self._beat(client, worker_id)
            while not self._stop and self._fatal is None:
                if self.membership.is_dead(worker_id):
                    # Fencing: the detector expired us (we stalled past
                    # dead_after) and our leases were re-queued — keep
                    # OUT of the ledger rather than double-complete.
                    self.ledger.requeue_worker(worker_id)
                    with self._lock:
                        self.stats["fenced"].append(worker_id)
                    return
                unit = self.ledger.lease(worker_id)
                if unit is None:
                    if self.ledger.all_done():
                        return
                    self._sleep(self.idle_wait)
                    continue
                seq = ctx.unit_seq
                ctx.unit_seq += 1
                try:
                    if self.injector is not None:
                        self.injector.maybe_fail_worker(worker_id, seq)
                    self._beat(client, worker_id)
                    metrics = self.run_unit(worker_id, client, unit)
                except InjectedWorkerDeath:
                    units = self.ledger.requeue_worker(worker_id)
                    self._record_death(worker_id, "injected kill", units)
                    return
                except ParameterServerUnavailable:
                    units = self.ledger.requeue_worker(worker_id)
                    self._record_death(worker_id, "ps unavailable", units)
                    client = self._await_ps(worker_id, client)
                    if client is None:
                        self._fatal = ParameterServerUnavailable(
                            "parameter server did not come back within "
                            f"{self.ps_recovery_grace}s grace"
                        )
                        return
                    continue  # resume with the fresh client
                except BaseException as exc:
                    units = self.ledger.requeue_worker(worker_id)
                    self._record_death(worker_id, repr(exc), units)
                    return
                counted, finished_epoch = self.ledger.complete(worker_id, unit)
                if counted:
                    with self._lock:
                        slot = self._epoch_metrics.setdefault(unit[0], {})
                        prev = slot.get(unit[1])
                        n = self._metric_counts.get((unit[0], unit[1]), 0)
                        if prev is None or not isinstance(metrics, dict):
                            slot[unit[1]] = metrics
                        else:
                            # Batch-range units: running mean per
                            # (epoch, partition) so the table keeps its
                            # whole-partition shape (equal weight per
                            # range; a short tail range is slightly
                            # overweighted — metrics noise, not ledger
                            # accounting).
                            slot[unit[1]] = {
                                k: (prev[k] * n + metrics[k]) / (n + 1)
                                for k in prev if k in metrics
                            }
                        self._metric_counts[(unit[0], unit[1])] = n + 1
                    self._note_repaired(unit)
                if finished_epoch is not None and self.on_epoch_complete is not None:
                    # Serialized: epoch fires run user callbacks and
                    # evaluators that are not thread-safe.
                    with self._fire_lock:
                        try:
                            self.on_epoch_complete(finished_epoch)
                        except BaseException as exc:
                            self._fatal = exc
                            return
        finally:
            if client is not None:
                try:
                    client.deregister(worker_id)
                except Exception:
                    pass
                if hasattr(client, "close"):
                    try:
                        client.close()
                    except Exception:
                        pass

    def _monitor_loop(self) -> None:
        """Publish PS membership + re-queue detector-expired workers.

        This is what rescues STALLED workers — a thread wedged in a
        device call can't raise, but it also can't heartbeat, so the
        detector expires it and its leases return to the queue. The
        monitor tolerates PS outages (workers own that recovery path).
        """
        client = None
        try:
            while not self._stop:
                if not any(
                    c.thread is not None and c.thread.is_alive()
                    for c in list(self._ctxs.values())
                ):
                    return
                try:
                    if client is None:
                        client = self.client_factory("monitor")
                    table = client.membership()
                except Exception:
                    if client is not None and hasattr(client, "close"):
                        try:
                            client.close()
                        except Exception:
                            pass
                    client = None
                    table = None
                if table is not None:
                    self.membership.publish(table)
                    for worker_id, entry in table.items():
                        if entry.get("state") != "dead":
                            continue
                        units = self.ledger.requeue_worker(worker_id)
                        if units:
                            self._record_death(
                                worker_id, "detector expiry", units
                            )
                self._sleep(self.monitor_poll)
        finally:
            if client is not None and hasattr(client, "close"):
                try:
                    client.close()
                except Exception:
                    pass
