"""PS durability: write-ahead version-tagged snapshots with warm restart.

The reference parameter server held weights in driver memory only — a PS
crash lost the fit (SURVEY.md §2.1/§5.3). ``SnapshotWAL`` gives the wire
servers a durable tail: after an update lands, the server (through
``WalWriter``) appends a **version-tagged snapshot** of the
``ParameterBuffer`` to disk, and a restarted server resumes from the
last durable version instead of its cold init.

On-disk format: one file per snapshot, named ``<version:016d>.epk``,
whose bytes are exactly one packed wire frame (``parameter.wire``:
``[EPK1][u32 hlen][JSON header][pad][payload]`` with ``ver`` in the
header) — the SAME codec the PS speaks on the wire, so there is one
serialization path to trust and the file decodes zero-copy. Writes go
tmp-file → fsync → atomic ``os.rename``, so a crash mid-append leaves at
worst a ``.tmp`` turd, never a torn ``.epk``; ``restore_latest`` still
validates frames (magic + header + payload bounds) and walks past a
corrupt tail to the newest decodable snapshot.

Client reconciliation after a warm restart is the wire protocol's job:
a restarted server mints a fresh **boot id**, and the version-gated pull
requires (boot, version) to match before answering not-modified — so a
client whose cached version numerically collides with the restored
counter still receives a full body (see ``parameter/server.py``).

Cold start: an empty/missing WAL directory raises ``NoCheckpointError``
(``elephas_tpu.checkpoint``) — callers branch to their cold init on it.
"""

from __future__ import annotations

import os
import threading
from pathlib import Path
from typing import List, Optional, Tuple

from elephas_tpu.parameter import wire

_SUFFIX = ".epk"
_TMP_PREFIX = ".tmp-"


def _no_checkpoint_error(msg: str):
    # Lazy import: the canonical NoCheckpointError lives with the Orbax
    # checkpoint code, and importing orbax at module scope would tax
    # every PS server import with orbax's startup cost.
    from elephas_tpu.checkpoint.checkpoint import NoCheckpointError

    return NoCheckpointError(msg)


class SnapshotWAL:
    """Version-tagged snapshot log over the packed wire codec.

    ``keep`` bounds disk: after each append, all but the newest ``keep``
    snapshots are pruned. Appends are serialized by an internal lock
    (PS handler threads may race on the snapshot cadence).
    """

    def __init__(self, directory: str, keep: int = 3):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.keep = keep
        self._lock = threading.Lock()

    def _path(self, version: int) -> Path:
        return self.directory / f"{version:016d}{_SUFFIX}"

    def versions(self) -> List[int]:
        """Durable snapshot versions, ascending (filename-derived; a
        corrupt file is discovered at restore, not here)."""
        out = []
        for p in self.directory.glob(f"*{_SUFFIX}"):
            stem = p.name[: -len(_SUFFIX)]
            if stem.isdigit():
                out.append(int(stem))
        return sorted(out)

    def latest_version(self) -> Optional[int]:
        versions = self.versions()
        return versions[-1] if versions else None

    def versions_after(self, version: Optional[int]) -> List[int]:
        """Durable snapshot versions strictly newer than ``version``,
        ascending — the tail a streaming follower has not applied yet.
        ``None`` means "nothing applied": the full durable history.
        With ``wal_every > 1`` the version counter is sparse on disk, so
        this is the honest unapplied-snapshot count where a plain
        ``latest - applied`` difference over-reports the lag."""
        if version is None:
            return self.versions()
        return [v for v in self.versions() if v > version]

    def read_version(self, version: int) -> Optional[bytes]:
        """Raw packed frame bytes of the durable snapshot at exactly
        ``version`` — the pinned-read plane's data source (rollout
        rollback / A-B reads that must not race live pushes). ``None``
        when that version is not on disk: pruned past ``keep``, or never
        snapshotted (with ``wal_every > 1`` the durable counter is
        sparse). Each ``.epk`` file is exactly one packed wire frame
        with ``ver`` in its header, so servers relay the bytes verbatim
        and the client's normal decode path validates them."""
        try:
            return self._path(version).read_bytes()
        except OSError:
            return None

    def append(self, tree, version: int) -> Path:
        """Durably persist ``tree`` tagged with ``version``.

        tmp-write → flush → fsync → atomic rename: a reader (or a
        restart) can never observe a half-written snapshot under the
        final name. Idempotent per version — an existing snapshot at
        ``version`` is left alone.
        """
        final = self._path(version)
        with self._lock:
            if final.exists():
                return final
            frames = wire.encode_tree(tree, version=version)  # lock-ok: snapshot encode under the WAL lock is the atomic-publish protocol
            tmp = self.directory / f"{_TMP_PREFIX}{version:016d}-{os.getpid()}"
            with open(tmp, "wb") as f:
                for chunk in frames.chunks:
                    f.write(chunk)
                f.flush()  # lock-ok: tmp-file durability before the atomic rename
                os.fsync(f.fileno())  # lock-ok: tmp-file durability before the atomic rename
            os.rename(tmp, final)
            self._prune_locked()
        return final

    def _prune_locked(self) -> None:
        for version in self.versions()[: -self.keep]:
            try:
                self._path(version).unlink()
            except OSError:
                pass  # already gone (concurrent restart pruning)

    def restore_latest(self) -> Tuple[int, dict]:
        """``(version, tree)`` of the newest DECODABLE snapshot.

        Walks versions newest-first, skipping truncated/corrupt files (a
        crash can only corrupt the tmp file thanks to the atomic rename,
        but belt-and-braces: external copies/partial disks happen).
        Raises ``NoCheckpointError`` when nothing decodable remains —
        the caller's cold-start branch.
        """
        versions = self.versions()
        for version in reversed(versions):
            try:
                buf = self._path(version).read_bytes()
                out = wire.decode(buf)
            except (OSError, wire.WireFormatError):
                continue
            if isinstance(out, wire.NotModified) or out.version != version:
                continue  # wrong frame kind / renamed file: not trusted
            return version, out.tree
        raise _no_checkpoint_error(
            f"no decodable snapshot under {self.directory} "
            f"({len(versions)} candidate file(s) scanned)"
        )


class WalWriter:
    """Snapshot cadence glue between a ``ParameterBuffer`` and its WAL.

    ``after_update()`` is called by the PS servers after each applied
    delta, BEFORE the ack goes out: when the buffer has advanced
    ``every`` or more versions past the last durable snapshot, the
    current state is appended synchronously — so an acked update at a
    snapshot boundary is durable by the time the worker sees the ack,
    and the durability lag is bounded by ``every`` updates everywhere
    else. ``every=1`` (default) makes every acked update durable at the
    cost of a full-model encode+fsync per push; raise it for throughput.
    """

    def __init__(self, buffer, wal: SnapshotWAL, every: int = 1):
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        self.buffer = buffer
        self.wal = wal
        self.every = every
        self._lock = threading.Lock()
        self._last_written = wal.latest_version() or 0

    @property
    def last_written(self) -> int:
        return self._last_written

    def after_update(self) -> bool:
        """Maybe-snapshot; True iff a snapshot was written."""
        if self.buffer.version - self._last_written < self.every:
            return False
        with self._lock:
            version, snap = self.buffer.get_numpy_with_version()
            if version - self._last_written < self.every:
                return False  # a racing handler already wrote this window
            self.wal.append(snap, version)
            self._last_written = version
            return True

    def sync(self) -> int:
        """Force a snapshot of the buffer's current state (server
        shutdown hook); returns the durable version."""
        with self._lock:
            version, snap = self.buffer.get_numpy_with_version()
            if version > self._last_written:
                self.wal.append(snap, version)
                self._last_written = version
            return self._last_written
