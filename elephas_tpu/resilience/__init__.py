"""Resilience layer: fault-tolerant, elastic async training.

The reference elephas inherited ALL of its fault tolerance from Spark
(task retry, executor replacement, driver-held state); the TPU rebuild
dropped Spark and, until this package, owned none of it — a PS crash or
a wedged worker killed the fit. Four pieces rebuild the story natively:

- ``liveness``  — worker heartbeats as wire frames, a server-side
  timeout+suspect ``FailureDetector``, and the membership table the
  trainer polls.
- ``wal``       — ``SnapshotWAL``: write-ahead version-tagged snapshots
  of the ``ParameterBuffer`` in the packed wire format; a restarted PS
  warm-restarts from the last durable version and clients reconcile
  through the (boot, version)-gated pull.
- ``elastic``   — ``UnitLedger`` + ``ElasticWorkerPool``: dead workers'
  frequency units re-queued to survivors, late joiners admitted
  mid-epoch, accounting exact under any interleaving.
- ``faults``    — ``FaultPlan``/``FaultInjector``: seeded, step-indexed
  drops/delays/duplicates of wire frames and kills/stalls of worker
  threads, so chaos tests replay deterministically.

Entry point for training: ``AsyncTrainer(..., elastic=True,
fault_plan=..., ps_wal_dir=...)`` (``engine.async_engine``).
"""

from elephas_tpu.resilience.elastic import (  # noqa: F401
    ElasticWorkerPool,
    UnitLedger,
)
from elephas_tpu.resilience.faults import (  # noqa: F401
    FaultInjector,
    FaultPlan,
    InjectedWorkerDeath,
    install,
)
from elephas_tpu.resilience.liveness import (  # noqa: F401
    ALIVE,
    DEAD,
    SUSPECT,
    FailureDetector,
    MembershipView,
)
from elephas_tpu.resilience.wal import SnapshotWAL, WalWriter  # noqa: F401
