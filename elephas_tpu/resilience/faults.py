"""Deterministic fault injection: seeded, step-indexed chaos that replays.

Chaos tests are worthless when the chaos is irreproducible — a flake
under random packet loss cannot be bisected. A ``FaultPlan`` therefore
makes every injected failure a **pure function of (seed, site)**:

- wire faults key on ``(direction, label, frame_seq)`` — the per-peer
  frame counter, NOT wall time — and the decision is drawn from a
  ``numpy`` generator seeded with ``[seed, site-hash]``, so run N and
  run N+1 of the same scenario drop/delay/duplicate the SAME frames;
- worker faults (``kill_worker_at`` / ``stall_worker_at``) key on
  ``(worker_id, unit_seq)`` — the worker's Nth leased frequency unit —
  and fire unconditionally at the planned site.

Every consulted decision is recorded in the plan's ``trace``; two runs
from the same seed produce identical ``trace_digest()`` values for the
same consulted sites, which is what the chaos tests pin.

Runtime binding: ``FaultInjector`` attaches a plan to live traffic. The
socket layer (``utils.sockets.send/receive``) consults the process-wide
injector installed by ``install()`` — a single ``None``-check when no
chaos is configured, so production traffic pays nothing. A "dropped"
frame raises ``ConnectionError`` at the injection site (the wire model:
the peer never saw it / the reply never arrived), which drives the SAME
client retry/fail-fast machinery a real network fault would. Worker
kills raise ``InjectedWorkerDeath`` at the unit boundary; the elastic
pool treats it exactly like a crashed worker thread (units re-queued).
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Dict, Iterable, List, Optional, Tuple, Union

import numpy as np

SEND = "send"
RECV = "recv"
_ANY = "*"


class InjectedWorkerDeath(RuntimeError):
    """A FaultPlan killed this worker thread at a planned unit."""


def _site_hash(kind: str, key: Tuple) -> int:
    return zlib.crc32(repr((kind, key)).encode())


def _as_seq_set(value: Union[int, Iterable[int], None]):
    if value is None:
        return frozenset()
    if isinstance(value, int):
        return frozenset((value,))
    return frozenset(int(v) for v in value)


class FaultPlan:
    """Seeded, step-indexed chaos schedule.

    ``drop``/``delay``/``duplicate``: probability per wire frame, either
    a float (all labels) or ``{label: p}`` with ``"*"`` as the default.
    ``delay_seconds``: sleep applied to delayed frames.
    ``partition``: ``{label: (start_seq, end_seq)}`` — every frame for
    ``label`` with ``start_seq <= seq < end_seq`` is dropped (a
    deterministic network partition window).
    ``kill_worker_at``/``stall_worker_at``: ``{worker_id: unit_seq}``
    (or a collection of unit_seqs) — the worker dies/stalls when it
    reaches that many leased units. ``stall_seconds`` is how long a
    stalled worker sleeps (choose it beyond the detector's
    ``dead_after`` to exercise expiry + re-queue).
    """

    def __init__(
        self,
        seed: int,
        drop: Union[float, Dict[str, float], None] = None,
        delay: Union[float, Dict[str, float], None] = None,
        duplicate: Union[float, Dict[str, float], None] = None,
        delay_seconds: float = 0.05,
        partition: Optional[Dict[str, Tuple[int, int]]] = None,
        kill_worker_at: Optional[Dict] = None,
        stall_worker_at: Optional[Dict] = None,
        stall_seconds: float = 0.5,
    ):
        self.seed = int(seed)
        self.drop = self._norm_prob(drop)
        self.delay = self._norm_prob(delay)
        self.duplicate = self._norm_prob(duplicate)
        self.delay_seconds = float(delay_seconds)
        self.partition = dict(partition or {})
        self.kill_worker_at = {
            str(k): _as_seq_set(v) for k, v in (kill_worker_at or {}).items()
        }
        self.stall_worker_at = {
            str(k): _as_seq_set(v) for k, v in (stall_worker_at or {}).items()
        }
        self.stall_seconds = float(stall_seconds)
        self._trace: List[Tuple] = []
        self._trace_lock = threading.Lock()

    @staticmethod
    def _norm_prob(value) -> Dict[str, float]:
        if value is None:
            return {}
        if isinstance(value, (int, float)):
            return {_ANY: float(value)}
        return {str(k): float(v) for k, v in value.items()}

    def _prob(self, table: Dict[str, float], label: str) -> float:
        return table.get(label, table.get(_ANY, 0.0))

    def _record(self, kind: str, key: Tuple, outcome) -> None:
        with self._trace_lock:
            self._trace.append((kind, key, outcome))

    @property
    def trace(self) -> List[Tuple]:
        with self._trace_lock:
            return list(self._trace)

    def trace_digest(self) -> int:
        """Order-independent digest of every consulted decision — two
        replays from the same seed that consult the same sites agree
        (thread scheduling reorders the trace list, never its set)."""
        with self._trace_lock:
            return zlib.crc32(repr(sorted(map(repr, self._trace))).encode())

    def _chance(self, kind: str, label: str, seq: int, p: float) -> bool:
        if p <= 0.0:
            return False
        if p >= 1.0:
            self._record(kind, (label, seq), True)
            return True
        rng = np.random.default_rng([self.seed, _site_hash(kind, (label, seq))])
        hit = bool(rng.random() < p)
        self._record(kind, (label, seq), hit)
        return hit

    # -- wire decisions (consulted by FaultInjector) ---------------------

    def frame_action(self, direction: str, label: str, seq: int) -> Tuple[str, float]:
        """``(action, delay_s)`` for one frame at ``(direction, label,
        seq)``; action is 'pass' | 'drop' | 'dup'. Pure in (seed, site)."""
        window = self.partition.get(label) or self.partition.get(_ANY)
        if window is not None and window[0] <= seq < window[1]:
            self._record("partition", (direction, label, seq), True)
            return "drop", 0.0
        if self._chance(f"drop-{direction}", label, seq,
                        self._prob(self.drop, label)):
            return "drop", 0.0
        action = "pass"
        if direction == SEND and self._chance(
            f"dup-{direction}", label, seq, self._prob(self.duplicate, label)
        ):
            action = "dup"
        delay_s = (
            self.delay_seconds
            if self._chance(f"delay-{direction}", label, seq,
                            self._prob(self.delay, label))
            else 0.0
        )
        return action, delay_s

    # -- worker decisions ------------------------------------------------

    def should_kill(self, worker_id, unit_seq: int) -> bool:
        hit = unit_seq in self.kill_worker_at.get(str(worker_id), ())
        if hit:
            self._record("kill", (str(worker_id), unit_seq), True)
        return hit

    def stall_for(self, worker_id, unit_seq: int) -> float:
        if unit_seq in self.stall_worker_at.get(str(worker_id), ()):
            self._record("stall", (str(worker_id), unit_seq),
                         self.stall_seconds)
            return self.stall_seconds
        return 0.0


class FaultInjector:
    """Binds a ``FaultPlan`` to live traffic.

    Sockets are labelled via ``label_socket(sock, label)`` (the elastic
    pool labels each worker's client connection with the worker id);
    unlabelled sockets share the ``"?"`` label. Frame sequence numbers
    are per ``(label, direction)`` so a label's Nth send is the same
    site in every replay, regardless of what other workers do.
    """

    def __init__(self, plan: FaultPlan, sleep: Callable[[float], None] = time.sleep):
        self.plan = plan
        self._sleep = sleep
        self._lock = threading.Lock()
        self._labels: Dict[int, str] = {}
        self._seqs: Dict[Tuple[str, str], int] = {}

    def label_socket(self, sock, label: str) -> None:
        with self._lock:
            self._labels[id(sock)] = str(label)

    def unlabel_socket(self, sock) -> None:
        with self._lock:
            self._labels.pop(id(sock), None)

    def _next_seq(self, label: str, direction: str) -> int:
        with self._lock:
            key = (label, direction)
            seq = self._seqs.get(key, 0)
            self._seqs[key] = seq + 1
            return seq

    def _frame_event(self, sock, direction: str) -> str:
        with self._lock:
            label = self._labels.get(id(sock), "?")
        seq = self._next_seq(label, direction)
        action, delay_s = self.plan.frame_action(direction, label, seq)
        if delay_s > 0.0:
            self._sleep(delay_s)
        if action == "drop":
            raise ConnectionError(
                f"fault-injected {direction} drop (label={label}, seq={seq})"
            )
        return action

    # -- hooks called from utils.sockets --------------------------------

    def on_send(self, sock) -> str:
        """'pass' or 'dup'; raises ConnectionError on a planned drop."""
        return self._frame_event(sock, SEND)

    def on_recv(self, sock) -> str:
        return self._frame_event(sock, RECV)

    # -- hooks called from the elastic pool ------------------------------

    def maybe_fail_worker(self, worker_id, unit_seq: int) -> None:
        """Raise/stall per the plan at a worker's unit boundary."""
        stall = self.plan.stall_for(worker_id, unit_seq)
        if stall > 0.0:
            self._sleep(stall)
        if self.plan.should_kill(worker_id, unit_seq):
            raise InjectedWorkerDeath(
                f"fault plan killed worker {worker_id} at unit {unit_seq}"
            )


def install(injector: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Install (or clear, with None) the process-wide injector consulted
    by ``utils.sockets.send/receive``. Returns the injector for
    with-style chaining. Tests MUST clear it in teardown."""
    from elephas_tpu.utils import sockets

    sockets.set_fault_injector(injector)
    return injector
