"""Worker liveness: heartbeats in, a timeout+suspect failure detector out.

The reference delegated liveness wholesale to Spark (a lost executor is
the scheduler's problem — SURVEY.md §5.3). Our wire rebuild (PR 4) made
worker/PS failures *observable* but nothing owned them: a worker that
stops pushing simply goes quiet. This module is the server-side half of
the resilience story: workers send heartbeat frames (``("h", id)`` on
the socket transport, ``POST /heartbeat/<id>`` on HTTP), the parameter
server feeds them into a ``FailureDetector``, and the trainer reads the
resulting membership table to drive re-queueing (``resilience.elastic``).

Detector model: timeout + suspect (the simple two-threshold cousin of
phi-accrual). A worker is

- ``alive``   while its last beat is younger than ``suspect_after``,
- ``suspect`` between ``suspect_after`` and ``dead_after`` — still
  counted as a member, but schedulers should stop routing NEW work to
  it, and
- ``dead``    past ``dead_after`` — its pending units are fair game for
  re-queueing. The transition is edge-triggered: ``sweep()`` reports
  each expiry exactly once and bumps ``ps_worker_expired_total``.

A beat from a dead worker *revives* it (rejoin-after-stall): the zombie
fencing that prevents a revived worker from double-completing work lives
in the ledger (``UnitLedger.complete`` counts each unit once), not here.

Clock discipline: everything reads time through the injected ``clock``
(``scripts/lint_blocking.py`` enforces no raw ``time.*()`` calls in this
package), so detector tests advance a fake clock instead of sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from elephas_tpu import obs

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"


class FailureDetector:
    """Timeout+suspect failure detector over worker heartbeats.

    ``suspect_after``/``dead_after`` are seconds since the last beat
    (``dead_after`` defaults to twice ``suspect_after``). Thread-safe:
    the PS handler threads beat concurrently with the trainer's monitor
    sweeping.
    """

    def __init__(
        self,
        suspect_after: float = 5.0,
        dead_after: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        register_metrics: bool = True,
    ):
        if suspect_after <= 0:
            raise ValueError(f"suspect_after must be > 0, got {suspect_after}")
        self.suspect_after = float(suspect_after)
        self.dead_after = (
            2.0 * self.suspect_after if dead_after is None else float(dead_after)
        )
        if self.dead_after < self.suspect_after:
            raise ValueError(
                f"dead_after ({self.dead_after}) must be >= suspect_after "
                f"({self.suspect_after})"
            )
        self._clock = clock
        self._lock = threading.Lock()
        self._last_beat: Dict[str, float] = {}
        self._beats: Dict[str, int] = {}
        self._dead: set = set()
        self._expired_total = (
            obs.default_registry().counter(
                "ps_worker_expired_total",
                help="workers declared dead by the PS failure detector",
            )
            if register_metrics
            else None
        )

    def beat(self, worker_id: str) -> None:
        """Record one heartbeat; a beat from a dead worker revives it."""
        worker_id = str(worker_id)
        with self._lock:
            revived = worker_id in self._dead
            dead_for = (
                self._clock() - self._last_beat[worker_id]
                if revived and worker_id in self._last_beat else None
            )
            self._last_beat[worker_id] = self._clock()
            self._beats[worker_id] = self._beats.get(worker_id, 0) + 1
            self._dead.discard(worker_id)
        if revived:
            # A dead→alive flap is either a stalled-then-unstuck worker
            # or a detector threshold set too tight — both worth a
            # flight-recorder entry (outside the lock: note() takes the
            # recorder's own lock).
            obs.default_flight_recorder().note(
                "heartbeat_flap", "warn", worker=worker_id,
                dead_for_s=round(dead_for, 3) if dead_for is not None else None,
            )

    def deregister(self, worker_id: str) -> None:
        """Clean exit: the worker leaves WITHOUT counting as an expiry."""
        worker_id = str(worker_id)
        with self._lock:
            self._last_beat.pop(worker_id, None)
            self._beats.pop(worker_id, None)
            self._dead.discard(worker_id)

    def _state_of(self, age: float) -> str:
        if age < self.suspect_after:
            return ALIVE
        if age < self.dead_after:
            return SUSPECT
        return DEAD

    def sweep(self) -> List[str]:
        """Edge-triggered expiry scan: returns the workers that crossed
        into ``dead`` SINCE the last sweep (each reported exactly once)
        and counts them in ``ps_worker_expired_total``."""
        now = self._clock()
        newly_dead = []
        with self._lock:
            for worker_id, last in self._last_beat.items():
                if worker_id in self._dead:
                    continue
                if now - last >= self.dead_after:
                    self._dead.add(worker_id)
                    newly_dead.append(worker_id)
        if newly_dead:
            if self._expired_total is not None:
                self._expired_total.inc(len(newly_dead))
            obs.default_flight_recorder().note(
                "worker_dead", "error", workers=list(newly_dead),
                dead_after_s=self.dead_after,
            )
        return newly_dead

    def membership(self) -> Dict[str, Dict]:
        """Current membership table ``{worker_id: {state, age_s, beats}}``.

        Runs a ``sweep()`` first so expiries are counted even when nobody
        polls ``sweep`` explicitly — reading the table IS the detector's
        evaluation point."""
        self.sweep()
        now = self._clock()
        with self._lock:
            return {
                worker_id: {
                    "state": DEAD if worker_id in self._dead
                    else self._state_of(now - last),
                    "age_s": now - last,
                    "beats": self._beats.get(worker_id, 0),
                }
                for worker_id, last in self._last_beat.items()
            }

    def state(self, worker_id: str) -> Optional[str]:
        """One worker's state, or None if it never beat."""
        return self.membership().get(str(worker_id), {}).get("state")


class MembershipView:
    """Trainer-side cache of the PS membership table.

    The elastic pool's monitor polls the PS (``client.membership()``)
    and publishes the table here; worker threads read it lock-cheap to
    check their own fencing state (a worker that was declared dead while
    stalled must not keep completing units)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._table: Dict[str, Dict] = {}

    def publish(self, table: Dict[str, Dict]) -> None:
        with self._lock:
            self._table = dict(table)

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return dict(self._table)

    def state(self, worker_id: str) -> Optional[str]:
        with self._lock:
            entry = self._table.get(str(worker_id))
        return entry.get("state") if entry else None

    def is_dead(self, worker_id: str) -> bool:
        return self.state(worker_id) == DEAD
