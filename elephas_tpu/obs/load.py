"""Engine load signals: one saturation score a router can dispatch on.

Everything the obs stack measures so far is retrospective — spans,
histograms, fleet rollups say what *happened*. The ROADMAP's replica
tier ("per-replica backpressure and queue-depth-aware dispatch") needs
the opposite: a present-tense answer to "how loaded is this engine
right now", cheap enough to compute every scheduler step and stable
enough to route on. This module is that answer:

- ``LoadSnapshot`` — an immutable point-in-time record of the raw
  saturation signals the scheduler already has in hand (queue depth
  against its bound, active decode slots against ``max_slots``, KV-pool
  free fraction) plus trailing rates derived from ``ServingMetrics``
  counters (admissions, rejects, token throughput) over a
  ``HistoryRing`` window.
- ``instant_load(snap)`` — a pure reduction of one snapshot to a raw
  saturation figure in [0, 1]: a weighted blend of slot occupancy,
  queue fullness, and KV-pool pressure, bumped toward 1.0 while the
  engine is actively shedding (non-zero reject rate). Monotone in
  queue depth and occupancy by construction — rising pressure can
  never *lower* the score.
- ``LoadScore`` — a time-based EWMA of the raw figure on the injected
  clock (``alpha = 1 - exp(-dt / tau)``), so a router sees a smoothed
  signal instead of per-step flicker, and seeded tests replay the
  exact same values with a fake clock.
- ``LoadTracker`` — the stateful composition the engine owns: feed it
  the scheduler's live signals each step (``observe()``), read the
  JSON document the opsd ``/load`` route serves (``snapshot()``).

The tracker mirrors the smoothed score into the default registry as a
``serving_load_score`` gauge (lazily bound, latched off on failure —
the same discipline as ``ServingMetrics``), which both rides the
history sampler's ``serving_`` prefix into ``/history`` rings and
reaches the fleet rollup as a per-proc gauge.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, Optional

from elephas_tpu.obs.history import HistoryRing

# Blend weights for the raw saturation figure. Occupancy leads (a full
# decode batch is the first hard resource), queue fullness second (work
# already committed but not placed), KV pressure third (the resource
# that admission actually blocks on).
WEIGHT_OCCUPANCY = 0.4
WEIGHT_QUEUE = 0.3
WEIGHT_KV = 0.2
WEIGHT_REJECT = 0.1

# A sustained reject rate at/above this (per second) reads as "fully
# shedding" and contributes the whole reject weight.
REJECT_RATE_FULL = 1.0

DEFAULT_TAU_S = 5.0
DEFAULT_RATE_WINDOW_S = 30.0


class LoadSnapshot:
    """One point-in-time reading of the engine's saturation signals."""

    __slots__ = (
        "t", "queue_depth", "queue_limit", "active", "max_slots",
        "kv_free_frac", "admit_rate", "reject_rate", "tokens_per_s",
        "kv_blocks_free", "kv_blocks_total", "prefix_hit_rate",
        "spec_accept_rate", "spec_tokens_per_step",
    )

    def __init__(self, *, t, queue_depth, queue_limit, active, max_slots,
                 kv_free_frac, admit_rate=0.0, reject_rate=0.0,
                 tokens_per_s=0.0, kv_blocks_free=None,
                 kv_blocks_total=None, prefix_hit_rate=None,
                 spec_accept_rate=None, spec_tokens_per_step=None):
        self.t = float(t)
        self.queue_depth = int(queue_depth)
        self.queue_limit = max(1, int(queue_limit))
        self.active = int(active)
        self.max_slots = max(1, int(max_slots))
        self.kv_free_frac = min(1.0, max(0.0, float(kv_free_frac)))
        self.admit_rate = max(0.0, float(admit_rate))
        self.reject_rate = max(0.0, float(reject_rate))
        self.tokens_per_s = max(0.0, float(tokens_per_s))
        # Paged-pool extras (None on contiguous pools): block-granular
        # KV pressure — ``kv_free_frac`` above is already block-derived
        # when these are present — plus the prefix-cache hit rate a
        # router can prefer replicas on.
        self.kv_blocks_free = (
            None if kv_blocks_free is None else int(kv_blocks_free)
        )
        self.kv_blocks_total = (
            None if kv_blocks_total is None else int(kv_blocks_total)
        )
        self.prefix_hit_rate = (
            None if prefix_hit_rate is None else float(prefix_hit_rate)
        )
        # Speculative-decode extras (None unless the engine runs
        # speculative=True and has harvested at least one window): the
        # trailing draft-token accept rate and emitted tokens per
        # lane-step a router/operator reads for decode efficiency.
        self.spec_accept_rate = (
            None if spec_accept_rate is None else float(spec_accept_rate)
        )
        self.spec_tokens_per_step = (
            None if spec_tokens_per_step is None
            else float(spec_tokens_per_step)
        )

    @property
    def occupancy(self) -> float:
        return min(1.0, self.active / self.max_slots)

    @property
    def queue_frac(self) -> float:
        return min(1.0, self.queue_depth / self.queue_limit)

    def to_dict(self) -> Dict[str, float]:
        out = {
            "t": self.t,
            "queue_depth": self.queue_depth,
            "queue_limit": self.queue_limit,
            "queue_frac": self.queue_frac,
            "active": self.active,
            "max_slots": self.max_slots,
            "occupancy": self.occupancy,
            "kv_free_frac": self.kv_free_frac,
            "admit_rate_per_s": self.admit_rate,
            "reject_rate_per_s": self.reject_rate,
            "tokens_per_s": self.tokens_per_s,
        }
        if self.kv_blocks_total is not None:
            out["kv_blocks_free"] = self.kv_blocks_free
            out["kv_blocks_total"] = self.kv_blocks_total
            out["prefix_hit_rate"] = self.prefix_hit_rate
        if self.spec_accept_rate is not None:
            out["spec_accept_rate"] = self.spec_accept_rate
        if self.spec_tokens_per_step is not None:
            out["spec_tokens_per_step"] = self.spec_tokens_per_step
        return out


def instant_load(snap: LoadSnapshot) -> float:
    """Reduce one snapshot to a raw saturation figure in [0, 1].

    A weighted blend rather than a max: a router wants to distinguish
    "queue half full, slots idle" from "slots full, queue empty", and a
    max collapses both onto one number. Each component is already in
    [0, 1] and the weights sum to 1, so the result needs no clamp —
    and is monotone non-decreasing in every pressure signal.
    """
    reject_pressure = min(1.0, snap.reject_rate / REJECT_RATE_FULL)
    return (
        WEIGHT_OCCUPANCY * snap.occupancy
        + WEIGHT_QUEUE * snap.queue_frac
        + WEIGHT_KV * (1.0 - snap.kv_free_frac)
        + WEIGHT_REJECT * reject_pressure
    )


class LoadScore:
    """Time-based EWMA of the raw load figure, on the injected clock.

    ``alpha = 1 - exp(-dt / tau)``: irregular observation spacing (the
    scheduler steps as fast as decode allows) still converges at the
    same wall-clock rate, and ``dt == 0`` degenerates to "no update" —
    replaying a seeded trace twice yields bit-identical scores.
    """

    __slots__ = ("tau_s", "_value", "_last_t")

    def __init__(self, tau_s: float = DEFAULT_TAU_S):
        if tau_s <= 0:
            raise ValueError(f"tau_s must be > 0, got {tau_s}")
        self.tau_s = float(tau_s)
        self._value: Optional[float] = None
        self._last_t: Optional[float] = None

    @property
    def value(self) -> Optional[float]:
        return self._value

    def update(self, raw: float, t: float) -> float:
        raw = min(1.0, max(0.0, float(raw)))
        if self._value is None:
            self._value, self._last_t = raw, float(t)
            return raw
        dt = max(0.0, float(t) - self._last_t)
        alpha = 1.0 - math.exp(-dt / self.tau_s)
        self._value += alpha * (raw - self._value)
        self._last_t = float(t)
        return self._value


class LoadTracker:
    """The engine-owned load plane: observe scheduler state, serve /load.

    ``observe()`` is called from the scheduler step with the signals it
    already holds — no locks taken inside the serving hot path beyond
    the tracker's own, no registry work unless the mirror is healthy.
    Counter-valued inputs (``rejected_total`` etc.) are pushed into
    rings and differentiated over ``rate_window_s`` so the snapshot
    carries trailing *rates*, not lifetime totals.
    """

    def __init__(self, *, tau_s: float = DEFAULT_TAU_S,
                 rate_window_s: float = DEFAULT_RATE_WINDOW_S,
                 clock: Callable[[], float] = time.monotonic,
                 capacity: int = 256):
        self.clock = clock
        self.rate_window_s = float(rate_window_s)
        self.score = LoadScore(tau_s=tau_s)
        self._lock = threading.Lock()
        self._admitted = HistoryRing(capacity)
        self._rejected = HistoryRing(capacity)
        self._tokens = HistoryRing(capacity)
        self._last: Optional[LoadSnapshot] = None
        self._raw: Optional[float] = None
        self._observations = 0
        self._registry_gauge = None  # lazy; False after a failed bind
        self._spec_gauges = None  # lazy; False after a failed bind

    def _mirror(self, value: float) -> None:
        if self._registry_gauge is None:
            try:
                from elephas_tpu import obs
                self._registry_gauge = obs.default_registry().gauge(
                    "serving_load_score",
                    help="EWMA engine saturation score in [0,1]",
                )
            except Exception:
                self._registry_gauge = False
        if self._registry_gauge:
            self._registry_gauge.set(value)

    def _mirror_spec(self, accept_rate, tokens_per_step) -> None:
        """Federate the speculative-decode gauges per proc, same
        lazy/latched discipline as the load-score mirror."""
        if self._spec_gauges is None:
            try:
                from elephas_tpu import obs
                reg = obs.default_registry()
                self._spec_gauges = (
                    reg.gauge(
                        "serving_spec_accept_rate",
                        help="draft tokens accepted / drafted in [0,1]",
                    ),
                    reg.gauge(
                        "serving_spec_tokens_per_step",
                        help="tokens emitted per speculative lane-step",
                    ),
                )
            except Exception:
                self._spec_gauges = False
        if self._spec_gauges:
            if accept_rate is not None:
                self._spec_gauges[0].set(accept_rate)
            if tokens_per_step is not None:
                self._spec_gauges[1].set(tokens_per_step)

    def observe(self, *, queue_depth, queue_limit, active, max_slots,
                kv_free_frac, admitted_total=0, rejected_total=0,
                tokens_total=0, now=None, kv_blocks_free=None,
                kv_blocks_total=None, prefix_hit_rate=None,
                spec_accept_rate=None,
                spec_tokens_per_step=None) -> LoadSnapshot:
        now = self.clock() if now is None else float(now)
        with self._lock:
            self._admitted.push(now, float(admitted_total))
            self._rejected.push(now, float(rejected_total))
            self._tokens.push(now, float(tokens_total))
            w = self.rate_window_s
            snap = LoadSnapshot(
                t=now, queue_depth=queue_depth, queue_limit=queue_limit,
                active=active, max_slots=max_slots, kv_free_frac=kv_free_frac,
                admit_rate=self._admitted.rate(w, now=now) or 0.0,
                reject_rate=self._rejected.rate(w, now=now) or 0.0,
                tokens_per_s=self._tokens.rate(w, now=now) or 0.0,
                kv_blocks_free=kv_blocks_free,
                kv_blocks_total=kv_blocks_total,
                prefix_hit_rate=prefix_hit_rate,
                spec_accept_rate=spec_accept_rate,
                spec_tokens_per_step=spec_tokens_per_step,
            )
            self._raw = instant_load(snap)
            score = self.score.update(self._raw, now)
            self._last = snap
            self._observations += 1
        self._mirror(score)
        if spec_accept_rate is not None or spec_tokens_per_step is not None:
            self._mirror_spec(spec_accept_rate, spec_tokens_per_step)
        return snap

    def snapshot(self) -> Dict[str, object]:
        """The opsd ``/load`` document: smoothed score + raw anatomy."""
        with self._lock:
            return {
                "score": self.score.value,
                "raw": self._raw,
                "tau_s": self.score.tau_s,
                "rate_window_s": self.rate_window_s,
                "observations": self._observations,
                "signals": self._last.to_dict() if self._last else None,
            }
