"""Metrics federation: one view over N per-process ops endpoints.

Everything the observability arc built so far (``/metrics``,
``/workers``, ``/alerts``, ``/trace``) is per-process; the ROADMAP's
replicated-serving and sharded-PS arcs are multi-process, and nobody
operates a fleet by curling N loopback ports by hand. This module is
the aggregation side:

- ``parse_prometheus_text`` — parses the exact exposition
  ``MetricsRegistry.expose_text()`` (or a stock Prometheus client)
  emits, including labeled families and cumulative histogram buckets.
  One wire format in and out: the aggregator speaks scrape text, not a
  private RPC, so any process with a ``/metrics`` route federates.
- ``ProcessRegistry`` — the roster. Each entry is one ops endpoint;
  its identity (role, boot id, worker_id, routes) comes from the
  endpoint's own ``/meta`` route at poll time, so a warm-restarted PS
  shows up under the same roster slot with a *new* boot id.
- ``FleetAggregator`` — polls every entry on an injectable clock and
  merges: counters **sum** across processes; gauges keep one child per
  process tagged ``proc=`` (summing queue depths across workers is a
  lie); fixed-bucket histograms merge **bucket-wise**, so fleet
  p50/p95/p99 are computed on the pooled distribution and stay within
  one bucket of exact — not an average of percentiles, which is
  statistically meaningless. ``/workers`` ledgers and ``/alerts``
  states roll up the same way.

Unreachable processes are **marked, never dropped**: a poll failure
flips the entry to ``stale``; once ``dead_after`` seconds pass since
its last successful poll it becomes ``dead``, and every flip lands in
the entry's transition log — a killed PS must read as *dead* in the
fleet view through the outage (chaos_bench --fleet pins exactly that),
not silently vanish from a dashboard.

The merged view is served from opsd's ``/fleet`` route and rendered by
``scripts/fleet_top.py``.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "FleetAggregator",
    "ProcessEntry",
    "ProcessRegistry",
    "bucket_percentile",
    "merge_metrics",
    "parse_prometheus_text",
]

# Roster entry lifecycle (also the vocabulary chaos assertions key on).
STATUSES = ("unknown", "alive", "stale", "dead")


# ---------------------------------------------------------------------------
# Exposition parsing
# ---------------------------------------------------------------------------

def _parse_labels(body: str) -> Dict[str, str]:
    """``k="v",k2="v2"`` (brace-stripped) → dict, honoring exposition
    escapes (``\\\\``, ``\\"``, ``\\n``) in values."""
    labels: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        eq = body.index("=", i)
        key = body[i:eq]
        if body[eq + 1] != '"':
            raise ValueError(f"unquoted label value at {body[eq:]!r}")
        j = eq + 2
        buf: List[str] = []
        while body[j] != '"':
            if body[j] == "\\":
                nxt = body[j + 1]
                buf.append({"n": "\n"}.get(nxt, nxt))
                j += 2
            else:
                buf.append(body[j])
                j += 1
        labels[key] = "".join(buf)
        i = j + 1
        if i < n and body[i] == ",":
            i += 1
    return labels


def _split_sample(line: str) -> Tuple[str, Dict[str, str], float]:
    """One exposition sample line → (name, labels, value)."""
    if "{" in line:
        name, rest = line.split("{", 1)
        # Label values are always quoted, so the labels block ends at
        # the last '"}' — robust to spaces/braces inside values.
        end = rest.rindex('"}')
        labels = _parse_labels(rest[:end + 1])
        value = float(rest[end + 2:].strip())
        return name, labels, value
    name, value = line.rsplit(None, 1)
    return name, {}, float(value)


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, Any]]:
    """Exposition text → ``{family_name: {"kind", "help", "samples",
    "histograms"}}``.

    ``samples`` is ``[(labels, value), ...]`` for counters/gauges (and
    untyped lines). ``histograms`` maps a canonical label key (le
    excluded) to ``{"labels", "bounds", "counts", "sum", "count"}`` with
    *per-bucket* (de-cumulated) counts plus a trailing +inf bucket —
    the shape bucket-wise merging wants.
    """
    families: Dict[str, Dict[str, Any]] = {}
    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}

    def family(name: str) -> Dict[str, Any]:
        fam = families.get(name)
        if fam is None:
            fam = families[name] = {
                "kind": kinds.get(name, "untyped"),
                "help": helps.get(name, ""),
                "samples": [],
                "histograms": {},
            }
        return fam

    # First pass for TYPE/HELP so ordering never matters.
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            kinds[name] = kind
        elif line.startswith("# HELP "):
            parts = line.split(None, 3)
            helps[parts[2]] = parts[3] if len(parts) > 3 else ""

    hist_names = {n for n, k in kinds.items() if k == "histogram"}
    # Raw cumulative bucket rows: name → labelkey → [(bound, cum)], and
    # the matching _sum/_count scalars.
    buckets: Dict[str, Dict[str, Dict[str, Any]]] = {}

    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, labels, value = _split_sample(line)
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in hist_names:
                base = name[:-len(suffix)]
                break
        if base is None:
            family(name)["samples"].append((labels, value))
            continue
        le = labels.pop("le", None)
        key = canonical_label_key(labels)
        row = buckets.setdefault(base, {}).setdefault(
            key, {"labels": labels, "cum": [], "sum": 0.0, "count": 0})
        if name.endswith("_bucket"):
            bound = float("inf") if le == "+Inf" else float(le)
            row["cum"].append((bound, value))
        elif name.endswith("_sum"):
            row["sum"] = value
        else:
            row["count"] = int(value)

    for base, rows in buckets.items():
        fam = family(base)
        for key, row in rows.items():
            cum = sorted(row["cum"])
            bounds = tuple(b for b, _ in cum if b != float("inf"))
            counts: List[int] = []
            prev = 0.0
            for _, c in cum:
                counts.append(int(c - prev))
                prev = c
            fam["histograms"][key] = {
                "labels": row["labels"],
                "bounds": bounds,
                "counts": counts,
                "sum": row["sum"],
                "count": row["count"],
            }
    return families


def canonical_label_key(labels: Dict[str, str]) -> str:
    """Deterministic ``{k="v",...}`` key (sorted); "" when unlabeled."""
    if not labels:
        return ""
    return "{" + ",".join(
        f'{k}="{labels[k]}"' for k in sorted(labels)) + "}"


def bucket_percentile(bounds: Tuple[float, ...], counts: List[int],
                      q: float) -> Optional[float]:
    """Quantile estimate from per-bucket counts (trailing +inf bucket).

    Linear interpolation inside the owning bucket, lower edge of the
    first bucket taken as 0 (every histogram in this package is a
    non-negative ladder — latencies, version lags, byte sizes). Without
    the per-process min/max the estimate can differ from
    ``Histogram.percentile`` by at most one bucket width — the merge
    tests pin exactly that tolerance.
    """
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q must be in [0, 1], got {q}")
    total = sum(counts)
    if total == 0:
        return None
    rank = q * total
    cum = 0.0
    lower = min(0.0, bounds[0]) if bounds else 0.0
    for i, c in enumerate(counts):
        upper = bounds[i] if i < len(bounds) else bounds[-1]
        if c and cum + c >= rank:
            frac = (rank - cum) / c
            return lower + (upper - lower) * frac
        cum += c
        if i < len(bounds):
            lower = bounds[i]
    return bounds[-1] if bounds else None


# ---------------------------------------------------------------------------
# Merging
# ---------------------------------------------------------------------------

def merge_metrics(per_proc: Dict[str, Dict[str, Dict[str, Any]]]
                  ) -> Dict[str, Any]:
    """Merge parsed expositions from N processes (see module docstring
    for the per-kind semantics). ``per_proc`` maps proc name → the
    output of ``parse_prometheus_text``."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    untyped: Dict[str, float] = {}
    # name+labelkey → accumulated histogram (or per-proc on mismatch).
    hists: Dict[str, Dict[str, Any]] = {}
    unmerged: List[str] = []

    for proc in sorted(per_proc):
        for name, fam in sorted(per_proc[proc].items()):
            kind = fam["kind"]
            for labels, value in fam["samples"]:
                if kind == "counter":
                    key = name + canonical_label_key(labels)
                    counters[key] = counters.get(key, 0.0) + value
                else:
                    # Gauges (and untyped info lines) are per-process
                    # facts; summing them across processes is a lie.
                    tagged = dict(labels)
                    tagged["proc"] = proc
                    key = name + canonical_label_key(tagged)
                    (gauges if kind == "gauge" else untyped)[key] = value
            for lkey, h in sorted(fam["histograms"].items()):
                key = name + lkey
                acc = hists.get(key)
                if acc is None:
                    hists[key] = {
                        "bounds": h["bounds"],
                        "counts": list(h["counts"]),
                        "sum": h["sum"],
                        "count": h["count"],
                        "procs": [proc],
                    }
                elif acc["bounds"] == h["bounds"]:
                    acc["counts"] = [a + b for a, b in
                                     zip(acc["counts"], h["counts"])]
                    acc["sum"] += h["sum"]
                    acc["count"] += h["count"]
                    acc["procs"].append(proc)
                else:
                    # Bucket ladders disagree: bucket-wise merge would
                    # corrupt percentiles. Keep it per-proc, visibly.
                    tagged = key + f'[proc={proc}]'
                    hists[tagged] = {**h, "counts": list(h["counts"]),
                                     "procs": [proc]}
                    unmerged.append(tagged)

    histograms: Dict[str, Any] = {}
    for key, acc in sorted(hists.items()):
        histograms[key] = {
            "count": acc["count"],
            "sum": acc["sum"],
            "p50": bucket_percentile(acc["bounds"], acc["counts"], 0.50),
            "p95": bucket_percentile(acc["bounds"], acc["counts"], 0.95),
            "p99": bucket_percentile(acc["bounds"], acc["counts"], 0.99),
            "procs": acc["procs"],
        }
    return {
        "counters": dict(sorted(counters.items())),
        "gauges": dict(sorted(gauges.items())),
        "untyped": dict(sorted(untyped.items())),
        "histograms": histograms,
        "unmerged_histograms": sorted(unmerged),
    }


def _merge_workers(per_proc: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Cluster roll-up of /workers ledgers: union of worker rows
    (colliding ids get proc-qualified keys), summed totals."""
    workers: Dict[str, Any] = {}
    owner: Dict[str, str] = {}
    totals = {"total_updates": 0, "unstamped_updates": 0}
    for proc in sorted(per_proc):
        doc = per_proc[proc]
        for wid, row in sorted(doc.get("workers", {}).items()):
            if wid in workers and owner[wid] != proc:
                workers[f"{owner[wid]}/{wid}"] = workers.pop(wid)
                workers[f"{proc}/{wid}"] = row
            elif f"{proc}/{wid}" not in workers and wid not in workers:
                workers[wid] = row
                owner[wid] = proc
        for k in totals:
            totals[k] += int(doc.get(k, 0))
    return {"workers": workers, **totals}


def _merge_alerts(per_proc: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Cluster roll-up of /alerts scrapes: active breaches and fired
    history tagged with their process, plus summed counts."""
    active: List[Dict[str, Any]] = []
    fired: List[Dict[str, Any]] = []
    for proc in sorted(per_proc):
        doc = per_proc[proc]
        for a in doc.get("active", []):
            active.append({**a, "proc": proc})
        for a in doc.get("fired", []):
            fired.append({**a, "proc": proc})
    return {
        "active": active,
        "fired": fired,
        "fired_total": len(fired),
        "fired_kinds": sorted({a.get("kind") for a in fired if "kind" in a}),
    }


# ---------------------------------------------------------------------------
# Roster + aggregator
# ---------------------------------------------------------------------------

class ProcessEntry:
    """One roster slot: an ops endpoint plus its observed lifecycle."""

    __slots__ = ("name", "url", "meta", "status", "last_ok", "last_error",
                 "polls", "failures", "transitions", "scrape")

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url.rstrip("/")
        self.meta: Dict[str, Any] = {}
        self.status = "unknown"
        self.last_ok: Optional[float] = None
        self.last_error: Optional[str] = None
        self.polls = 0
        self.failures = 0
        # [(t, status), ...] — every status flip, for chaos assertions.
        self.transitions: List[Tuple[float, str]] = []
        # Last successful scrape bodies: {"metrics", "workers", "alerts"}.
        self.scrape: Dict[str, Any] = {}

    def _set_status(self, status: str, now: float) -> None:
        if status != self.status:
            self.status = status
            self.transitions.append((now, status))

    def to_dict(self, now: float) -> Dict[str, Any]:
        return {
            "url": self.url,
            "status": self.status,
            "meta": self.meta,
            "last_ok_s_ago": (None if self.last_ok is None
                              else now - self.last_ok),
            "last_error": self.last_error,
            "polls": self.polls,
            "failures": self.failures,
            "transitions": [[t, s] for t, s in self.transitions],
        }


class ProcessRegistry:
    """The fleet roster (thread-safe). Entries are added explicitly —
    by chaos_bench, by an operator config, by whatever supervises the
    fleet — and are never removed by polling: death is a *state*."""

    def __init__(self):
        self._entries: Dict[str, ProcessEntry] = {}
        self._lock = threading.Lock()

    def add(self, url: str, name: Optional[str] = None) -> ProcessEntry:
        with self._lock:
            if name is None:
                name = f"proc{len(self._entries)}"
            entry = self._entries.get(name)
            if entry is None:
                entry = self._entries[name] = ProcessEntry(name, url)
            else:
                entry.url = url.rstrip("/")  # re-point a known slot
            return entry

    def get(self, name: str) -> Optional[ProcessEntry]:
        with self._lock:
            return self._entries.get(name)

    def entries(self) -> List[ProcessEntry]:
        with self._lock:
            return [self._entries[k] for k in sorted(self._entries)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def _default_fetch(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


class FleetAggregator:
    """Polls the roster and serves the merged view (see module doc).

    ``poll()`` is explicitly driven on an injectable clock — by a bench
    loop, by ``fleet_top --interval``, by tests — there is no hidden
    thread, so a seeded chaos run replays the exact same transition
    sequence. ``dead_after`` is the stale→dead promotion window,
    measured from the last *successful* poll.
    """

    def __init__(self, registry: Optional[ProcessRegistry] = None,
                 dead_after: float = 10.0, timeout: float = 2.0,
                 clock=time.monotonic,
                 fetch: Callable[[str, float], bytes] = _default_fetch):
        self.registry = registry if registry is not None else ProcessRegistry()
        self.dead_after = float(dead_after)
        self.timeout = float(timeout)
        self.clock = clock
        self.fetch = fetch
        self.polls = 0

    def add(self, url: str, name: Optional[str] = None) -> ProcessEntry:
        return self.registry.add(url, name=name)

    # -- polling ------------------------------------------------------------

    def _poll_one(self, entry: ProcessEntry, now: float) -> bool:
        try:
            meta = json.loads(self.fetch(f"{entry.url}/meta", self.timeout))
            metrics = parse_prometheus_text(
                self.fetch(f"{entry.url}/metrics", self.timeout).decode())
            workers = json.loads(
                self.fetch(f"{entry.url}/workers", self.timeout))
            alerts = json.loads(
                self.fetch(f"{entry.url}/alerts", self.timeout))
        except Exception as exc:
            entry.failures += 1
            entry.last_error = repr(exc)
            ref = entry.last_ok
            # Never been reachable → stale until dead_after from first
            # sighting of trouble; afterwards, from the last good poll.
            if ref is None and entry.transitions:
                ref = entry.transitions[0][0]
            if ref is not None and now - ref > self.dead_after:
                entry._set_status("dead", now)
            else:
                entry._set_status("stale", now)
            return False
        scrape = {"metrics": metrics, "workers": workers, "alerts": alerts}
        # Saturation/goodput routes are OPTIONAL per process: a roster
        # can mix newer engines with older procs (or fakes) that don't
        # serve them, and their absence must not fail the whole poll —
        # each is fetched in its own tolerant attempt.
        for route in ("/load", "/slo", "/replicas", "/incidents",
                      "/trials", "/tenants", "/tiers", "/rollout"):
            try:
                scrape[route[1:]] = json.loads(
                    self.fetch(f"{entry.url}{route}", self.timeout))
            except Exception:
                pass
        entry.meta = meta
        entry.scrape = scrape
        entry.last_ok = now
        entry.last_error = None
        entry._set_status("alive", now)
        return True

    def poll(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One pass over every roster entry; returns an ok/failed tally
        (bench loops time this call for the scrape-cost gate)."""
        if now is None:
            now = self.clock()
        ok = failed = 0
        for entry in self.registry.entries():
            entry.polls += 1
            if self._poll_one(entry, now):
                ok += 1
            else:
                failed += 1
        self.polls += 1
        return {"t": now, "ok": ok, "failed": failed}

    # -- read-out -----------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The merged fleet view — opsd's ``/fleet`` route serves this.

        Merges the *last-known* scrape of every entry (a dead PS keeps
        contributing its final counter values — dropping them would
        deflate fleet totals mid-outage) and labels each process with
        its current status so consumers can tell.
        """
        if now is None:
            now = self.clock()
        entries = self.registry.entries()
        per_metrics = {e.name: e.scrape["metrics"]
                       for e in entries if "metrics" in e.scrape}
        per_workers = {e.name: e.scrape["workers"]
                       for e in entries if "workers" in e.scrape}
        per_alerts = {e.name: e.scrape["alerts"]
                      for e in entries if "alerts" in e.scrape}
        # Per-proc saturation/goodput views: like gauges, these are NOT
        # summed (a fleet-total load score is a lie) — consumers key by
        # process name and read the status alongside.
        per_load = {e.name: e.scrape["load"]
                    for e in entries if "load" in e.scrape}
        per_slo = {e.name: e.scrape["slo"]
                   for e in entries if "slo" in e.scrape}
        # A serving router's /replicas roster: only procs that serve
        # the route (and returned a non-empty roster) contribute, so a
        # mixed fleet of engines + one router reads naturally.
        per_replicas = {e.name: e.scrape["replicas"]
                        for e in entries
                        if e.scrape.get("replicas", {}).get("replicas")}
        # Durable-store meta (/incidents): only procs with a mounted
        # telemetry store contribute — the fleet board's DISK column
        # reads bytes + last-persisted age from here.
        per_incidents = {e.name: e.scrape["incidents"]
                         for e in entries
                         if e.scrape.get("incidents", {}).get("meta")}
        # Tuner searches (/trials): only procs actually driving one
        # contribute (a non-empty trial table) — the board shows the
        # search through whichever process hosts the runner.
        per_trials = {e.name: e.scrape["trials"]
                      for e in entries
                      if e.scrape.get("trials", {}).get("trials")}
        # Per-tenant cost ledgers (/tenants): only procs with a live
        # ledger contribute (a non-empty tenant table). Counters union
        # tenant-wise across replicas — a tenant's fleet bill is the
        # sum of its per-replica bills — via the same merge the
        # router's own /tenants route uses.
        per_tenants = {e.name: e.scrape["tenants"]
                       for e in entries
                       if e.scrape.get("tenants", {}).get("tenants")}
        # Disaggregated tier topology (/tiers): only routers actually
        # running tiers contribute (a non-empty tier table) — like
        # /replicas, this is a per-router document, never summed.
        per_tiers = {e.name: e.scrape["tiers"]
                     for e in entries
                     if e.scrape.get("tiers", {}).get("tiers")}
        # Live-delivery plane (/rollout): only routers with an attached
        # RolloutController contribute (an active plane) — a per-router
        # document like /tiers, never summed.
        per_rollout = {e.name: e.scrape["rollout"]
                       for e in entries
                       if e.scrape.get("rollout", {}).get("active")}
        from elephas_tpu.obs.tenancy import merge_tenant_docs
        merged_tenants = merge_tenant_docs(
            [per_tenants[k] for k in sorted(per_tenants)])
        status_counts: Dict[str, int] = {}
        for e in entries:
            status_counts[e.status] = status_counts.get(e.status, 0) + 1
        return {
            "t": now,
            "polls": self.polls,
            "dead_after_s": self.dead_after,
            "status_counts": status_counts,
            "processes": {e.name: e.to_dict(now) for e in entries},
            "metrics": merge_metrics(per_metrics),
            "workers": _merge_workers(per_workers),
            "alerts": _merge_alerts(per_alerts),
            "load": per_load,
            "slo": per_slo,
            "replicas": per_replicas,
            "incidents": per_incidents,
            "trials": per_trials,
            "per_tenants": per_tenants,
            "tenants": merged_tenants,
            "tiers": per_tiers,
            "rollout": per_rollout,
        }
