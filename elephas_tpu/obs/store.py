"""Durable telemetry store: append-only segment journal for post-mortem.

Every other observability surface — flight ring, alert engine, history
rings, span tracer — lives in process memory; a SIGKILL loses all of it
except the ``flight-<boot>.json`` that only a *clean* ``kill()`` writes.
The telemetry store closes that gap: a per-process, append-only,
segment-rotated on-disk journal that the in-memory surfaces tee into at
event time, so "what happened, in what order" survives the process and
``obs/incident.py`` can rebuild the story from disk alone.

On-disk format (one directory per process slot, conventionally
``<wal_dir>/telemetry``): segment files named
``seg-<seq:08d>-<boot>.etj``, each a run of framed records —
``[ETJ1][u32 len][JSON body]`` — mirroring the packed wire codec's
magic + length framing (``parameter/wire.py``) at journal granularity.
Appends are ``write()+flush()`` per record (a killed *process* loses
nothing the kernel already holds; only a machine crash can lose the
unsynced tail), and ``fsync`` runs at segment rotation and ``sync()``,
so telemetry loss under SIGKILL is bounded to the current unsynced
segment. Like ``resilience/wal.py``, readers never trust the tail: a
torn final frame is walked past (and truncated on warm reopen), noted
as a ``store_corrupt_tail`` flight event.

Each record carries BOTH clocks (``wall_s`` + ``mono_s``) plus the boot
id and role, which is what lets ``IncidentBuilder`` clock-align N
processes' journals the way ``trace_report.merge_dumps`` aligns trace
dumps, and stitch a warm restart (same directory, new boot id) into one
story.

Disk is bounded: ``keep`` segments per boot, pruned oldest-first at
rotation, with the live total published as the ``obs_store_bytes``
gauge (per-role, lazily bound like the flight recorder's drop counter).

Record kinds journaled (``k`` field): ``flight`` (anomaly events at
``note()`` time), ``alert`` (fire/clear transitions), ``metric``
(HistorySampler ticks), ``span`` (completed span summaries),
``lifecycle`` (the store's own boot/close/heal marks — the roster
transitions of the post-mortem timeline).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple
from elephas_tpu.utils import locksan

__all__ = [
    "TelemetryStore", "iter_records", "read_store", "scan_segment",
    "store_dirs", "RECORD_KINDS", "SEGMENT_SUFFIX",
]

_MAGIC = b"ETJ1"
_LEN = struct.Struct("!I")
_HEADER = len(_MAGIC) + _LEN.size
#: Per-record sanity bound — a length field past this is corruption,
#: not a record (records are small JSON; segments rotate at ~128 KiB).
_MAX_RECORD = 8 * 1024 * 1024

SEGMENT_SUFFIX = ".etj"

#: The journal's record vocabulary (the ``k`` field).
RECORD_KINDS = ("flight", "alert", "metric", "span", "lifecycle")


def _segment_name(seq: int, boot: str) -> str:
    return f"seg-{seq:08d}-{boot}{SEGMENT_SUFFIX}"


def _parse_segment_name(name: str) -> Optional[Tuple[int, str]]:
    """``(seq, boot)`` from a segment filename, None for foreign files."""
    if not (name.startswith("seg-") and name.endswith(SEGMENT_SUFFIX)):
        return None
    stem = name[len("seg-"):-len(SEGMENT_SUFFIX)]
    seq_s, sep, boot = stem.partition("-")
    if not sep or not seq_s.isdigit() or not boot:
        return None
    return int(seq_s), boot


def _frame(record: Dict[str, Any]) -> bytes:
    body = json.dumps(record, separators=(",", ":")).encode("utf-8")
    return _MAGIC + _LEN.pack(len(body)) + body


def scan_segment(path: str) -> Tuple[List[Dict[str, Any]], Optional[int]]:
    """Decode one segment, tolerating a torn tail.

    Returns ``(records, good_bytes)`` where ``good_bytes`` is None for a
    fully clean segment, else the byte offset of the last valid frame
    boundary — everything past it is a torn/corrupt tail (crash
    mid-append, partial flush). Mirrors ``SnapshotWAL.restore_latest``'s
    walk-past-the-corrupt-tail discipline at record granularity.
    """
    try:
        buf = Path(path).read_bytes()
    except OSError:
        return [], 0
    records: List[Dict[str, Any]] = []
    off = 0
    while off < len(buf):
        head = buf[off:off + _HEADER]
        if len(head) < _HEADER or head[:len(_MAGIC)] != _MAGIC:
            return records, off
        (length,) = _LEN.unpack(head[len(_MAGIC):])
        end = off + _HEADER + length
        if length > _MAX_RECORD or end > len(buf):
            return records, off
        try:
            rec = json.loads(buf[off + _HEADER:end].decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return records, off
        if not isinstance(rec, dict):
            return records, off
        records.append(rec)
        off = end
    return records, None


def iter_records(directory: str) -> Tuple[List[Dict[str, Any]], List[str]]:
    """All decodable records of one store directory, in append order
    (segment seq, then in-file order), plus the segment paths whose tail
    was corrupt. Purely read-only — safe on a dead process's directory
    and on foreign boots' segments alike."""
    d = Path(directory)
    segs = []
    for p in sorted(d.glob(f"seg-*{SEGMENT_SUFFIX}")):
        parsed = _parse_segment_name(p.name)
        if parsed is not None:
            segs.append((parsed[0], p))
    records: List[Dict[str, Any]] = []
    corrupt: List[str] = []
    for _, p in sorted(segs, key=lambda sp: (sp[0], sp[1].name)):
        recs, good = scan_segment(str(p))
        records.extend(recs)
        if good is not None:
            corrupt.append(str(p))
    return records, corrupt


def read_store(directory: str) -> Dict[str, Any]:
    """Post-mortem read-out of one store directory: records + disk
    stats, computable with the owning process long dead."""
    records, corrupt = iter_records(directory)
    d = Path(directory)
    nbytes = 0
    nsegs = 0
    for p in d.glob(f"seg-*{SEGMENT_SUFFIX}"):
        if _parse_segment_name(p.name) is not None:
            nsegs += 1
            try:
                nbytes += p.stat().st_size
            except OSError:
                pass
    return {
        "dir": str(d),
        "records": records,
        "segments": nsegs,
        "bytes": nbytes,
        "corrupt_tails": corrupt,
    }


def store_dirs(root: str) -> List[str]:
    """Discover store directories under ``root`` (any directory holding
    at least one segment file), sorted — the post-mortem CLI's walk."""
    out = set()
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            if _parse_segment_name(name) is not None:
                out.add(dirpath)
                break
    return sorted(out)


class TelemetryStore:
    """Append-only, segment-rotated, boot-tagged telemetry journal.

    One instance per process slot, mounted next to the WAL. Thread-safe:
    the teeing surfaces (flight recorder, alert engine, history sampler,
    tracer) append from their own threads. ``keep`` bounds disk per
    boot — rotation prunes THIS boot's oldest segments only, so a warm
    restart sharing the directory never eats a predecessor's evidence
    beyond its own budget.
    """

    def __init__(self, directory: str, role: str = "", boot: str = "",
                 keep: int = 8, segment_bytes: int = 128 * 1024,
                 recent: int = 64, clock=time.monotonic,
                 registry=None, flight=None):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        if segment_bytes < 1024:
            raise ValueError(
                f"segment_bytes must be >= 1024, got {segment_bytes}")
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.role = role
        self.boot = boot or os.urandom(6).hex()
        self.keep = keep
        self.segment_bytes = segment_bytes
        self.clock = clock
        self.flight = flight
        self._registry = registry
        self._gauge = None  # lazily bound (mirrors flight's drop counter)
        self._lock = locksan.make_lock("TelemetryStore._lock")
        self._seq = 0          # record sequence, this boot
        self._records = 0
        self._rotations = 0
        self._pruned = 0
        self._healed = 0
        self._last_wall: Optional[float] = None
        self._last_mono: Optional[float] = None
        self._recent: deque = deque(maxlen=recent)
        self._closed = False
        self._fh = None
        next_seg = self._heal_and_next_seq()
        # Byte accounting is incremental (per-record stat/glob would tax
        # the hot tee paths): foreign boots' bytes counted once at open,
        # own bytes tracked at append/prune.
        self._other_bytes = 0
        for p in self.directory.glob(f"seg-*{SEGMENT_SUFFIX}"):
            if _parse_segment_name(p.name) is not None:
                try:
                    self._other_bytes += p.stat().st_size
                except OSError:
                    pass
        self._my_bytes = 0
        self._seg_seq = next_seg
        self._seg_path = self.directory / _segment_name(next_seg, self.boot)
        self._seg_bytes = 0
        self._fh = open(self._seg_path, "ab")
        self.record_lifecycle("boot")

    # -- open-time healing -------------------------------------------------

    def _heal_and_next_seq(self) -> int:
        """Truncate torn tails of pre-existing segments (a predecessor
        boot died mid-append) and return the next free segment seq.

        Safe because segment files are single-writer (boot id in the
        name) and this store has not opened its own segment yet; a torn
        tail can only belong to a dead boot. Healing is noted loudly —
        a ``store_corrupt_tail`` flight event per truncated file."""
        max_seq = -1
        for p in sorted(self.directory.glob(f"seg-*{SEGMENT_SUFFIX}")):
            parsed = _parse_segment_name(p.name)
            if parsed is None:
                continue
            seq, boot = parsed
            max_seq = max(max_seq, seq)
            if boot == self.boot:
                continue
            _, good = scan_segment(str(p))
            if good is None:
                continue
            try:
                size = p.stat().st_size
                with open(p, "ab") as f:
                    f.truncate(good)
            except OSError:
                continue
            self._healed += 1
            if self.flight is not None:
                self.flight.note(
                    "store_corrupt_tail", "warn", path=p.name,
                    truncated_bytes=size - good, kept_bytes=good,
                )
        return max_seq + 1

    # -- append path -------------------------------------------------------

    def record(self, k: str, data: Dict[str, Any],
               wall_s: Optional[float] = None,
               mono_s: Optional[float] = None,
               severity: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Journal one record. ``wall_s``/``mono_s`` default to now —
        pass the event's own stamps when teeing (the flight recorder
        already read both clocks at the anomaly site)."""
        if k not in RECORD_KINDS:
            raise ValueError(f"record kind must be one of {RECORD_KINDS}, "
                             f"got {k!r}")
        rec: Dict[str, Any] = {
            "k": k,
            "wall_s": time.time() if wall_s is None else wall_s,
            "mono_s": self.clock() if mono_s is None else mono_s,
            "boot": self.boot,
            "role": self.role,
            "seq": 0,  # patched under the lock
            "data": data,
        }
        if severity is not None:
            rec["severity"] = severity
        with self._lock:
            if self._closed:
                return None
            rec["seq"] = self._seq
            self._seq += 1
            frame = _frame(rec)
            if (self._seg_bytes and
                    self._seg_bytes + len(frame) > self.segment_bytes):
                self._rotate_locked()
            try:
                self._fh.write(frame)
                self._fh.flush()  # lock-ok: single-writer journal; write+flush is the record boundary
            except OSError:
                return None  # disk gone: telemetry must never crash hosts
            self._seg_bytes += len(frame)
            self._my_bytes += len(frame)
            self._records += 1
            self._last_wall = rec["wall_s"]
            self._last_mono = rec["mono_s"]
            self._recent.append(rec)
        self._set_gauge()
        return rec

    def _rotate_locked(self) -> None:
        """Seal the current segment (fsync — it becomes durable against
        machine crash, not just process death) and open the next."""
        try:
            self._fh.flush()  # lock-ok: segment seal; rotation must be atomic wrt writers
            os.fsync(self._fh.fileno())  # lock-ok: segment seal fsync
        except OSError:
            pass
        self._fh.close()
        self._seg_seq += 1
        self._seg_path = self.directory / _segment_name(self._seg_seq,
                                                        self.boot)
        self._fh = open(self._seg_path, "ab")
        self._seg_bytes = 0
        self._rotations += 1
        self._prune_locked()

    def _prune_locked(self) -> None:
        """keep-N per boot, oldest first — only THIS boot's segments."""
        mine = []
        for p in self.directory.glob(f"seg-*{SEGMENT_SUFFIX}"):
            parsed = _parse_segment_name(p.name)
            if parsed is not None and parsed[1] == self.boot:
                mine.append((parsed[0], p))
        for _, p in sorted(mine)[:-self.keep]:
            try:
                size = p.stat().st_size
                p.unlink()
                self._my_bytes -= size
                self._pruned += 1
            except OSError:
                pass

    def _set_gauge(self) -> None:
        gauge = self._gauge
        if gauge is None:
            try:
                registry = self._registry
                if registry is None:
                    from elephas_tpu import obs

                    registry = obs.default_registry()
                gauge = registry.gauge(
                    "obs_store_bytes",
                    help="on-disk bytes of the durable telemetry store",
                    labelnames=("role",),
                )
            except Exception:
                gauge = False  # registry unavailable: stop trying
            self._gauge = gauge
        if gauge:
            gauge.labels(role=self.role or "unknown").set(
                float(self._other_bytes + self._my_bytes))

    # -- teeing convenience (one per journaled surface) --------------------

    def record_flight(self, event) -> None:
        """Tee one ``FlightEvent`` at ``note()`` time (its own stamps)."""
        self.record(
            "flight",
            {"kind": event.kind, "severity": event.severity,
             "trace_id": event.trace_id, "detail": event.detail},
            wall_s=event.wall_s, mono_s=event.mono_s,
            severity=event.severity,
        )

    def record_alert(self, transition: str, alert: Dict[str, Any]) -> None:
        """Tee one alert transition (``fire`` | ``clear``)."""
        self.record(
            "alert", dict(alert, transition=transition),
            severity=alert.get("severity") if transition == "fire"
            else "info",
        )

    def record_metrics(self, values: Dict[str, float], tick: int) -> None:
        """Tee one HistorySampler tick (the sampled name→value map)."""
        self.record("metric", {"values": values, "tick": tick})

    def record_span(self, summary: Dict[str, Any],
                    mono_s: Optional[float] = None) -> None:
        """Tee one completed span summary."""
        self.record("span", summary, mono_s=mono_s)

    def record_lifecycle(self, event: str, **detail) -> None:
        """Journal a store/process lifecycle mark (boot, close, ...)."""
        data: Dict[str, Any] = {"event": event}
        if self._healed and event == "boot":
            data["healed_tails"] = self._healed
        data.update(detail)
        self.record("lifecycle", data, severity="info")

    def set_role(self, role: str) -> None:
        """Re-stamp subsequent records (standby promotion). The old
        role's gauge child zeroes so the fleet view doesn't double-count
        a process that changed hats mid-boot."""
        old, self.role = self.role, role
        if old != role and self._gauge:
            try:
                self._gauge.labels(role=old or "unknown").set(0.0)
            except Exception:
                pass
        self._set_gauge()

    # -- durability + lifecycle --------------------------------------------

    def sync(self) -> None:
        """fsync the current segment (clean-shutdown / checkpoint hook)."""
        with self._lock:
            if self._closed:
                return
            try:
                self._fh.flush()  # lock-ok: durability barrier, serialized with writers by design
                os.fsync(self._fh.fileno())  # lock-ok: durability barrier
            except OSError:
                pass

    def close(self, reason: str = "close") -> None:
        """Final lifecycle record + fsync + close. Idempotent."""
        with self._lock:
            if self._closed:
                return
        self.record_lifecycle(reason)
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._fh.flush()  # lock-ok: final seal before close
                os.fsync(self._fh.fileno())  # lock-ok: final seal before close
            except OSError:
                pass
            self._fh.close()

    # -- read-out ----------------------------------------------------------

    def disk_bytes(self) -> int:
        total = 0
        for p in self.directory.glob(f"seg-*{SEGMENT_SUFFIX}"):
            if _parse_segment_name(p.name) is not None:
                try:
                    total += p.stat().st_size
                except OSError:
                    pass
        return total

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            last_mono = self._last_mono
            out = {
                "dir": str(self.directory),
                "role": self.role,
                "boot": self.boot,
                "records": self._records,
                "segments": self._seg_seq + 1,
                "rotations": self._rotations,
                "pruned_segments": self._pruned,
                "healed_tails": self._healed,
                "last_record_wall_s": self._last_wall,
            }
        out["bytes"] = self.disk_bytes()
        out["last_record_age_s"] = (
            None if last_mono is None else max(0.0, self.clock() - last_mono)
        )
        return out

    def doc(self) -> Dict[str, Any]:
        """The ``/incidents`` ops route payload: live view of the local
        store — disk stats + the most recent records."""
        with self._lock:
            recent = list(self._recent)
        return {"meta": self.stats(), "recent": recent}
