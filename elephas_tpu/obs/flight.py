"""Flight recorder: bounded ring of structured anomaly events.

Metrics answer "how many rejections"; traces answer "where the time
went"; neither answers "what went *wrong* around 14:03:07, in what
order, on which trace" after a PS kill. The flight recorder is that
third surface: every anomaly the system already detects — retrace
storms, heartbeat flaps, stale-delta rejections, backpressure
rejections, deadline evictions, WAL restores — drops one structured
event into a bounded ring, tagged with severity and the trace context
active at the anomaly site, so a merged chaos trace and the anomaly log
join on trace id.

Recording is a clock read + dict build + deque append under a small
lock — cheap enough to stay on unconditionally (anomalies are rare by
definition; a recorder hot enough to matter is itself the anomaly, and
the ring bounds the damage). The ring keeps the *recent* past and
counts what it overwrites (``dropped``), mirroring the span tracer's
truncation honesty.

Read-out paths: ``events()``/``snapshot()`` for tests and the ops
endpoint's ``/flight`` route; ``dump(path)`` for the crash path — PS
``kill()`` writes the ring to disk *before* severing connections, so a
post-mortem has the anomaly log even though the process skipped every
clean-shutdown sync.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from elephas_tpu.obs import trace as _trace
from elephas_tpu.utils import locksan

__all__ = ["FlightEvent", "FlightRecorder", "KINDS", "NULL_FLIGHT_RECORDER"]

#: Allowed severities, in increasing order of alarm.
SEVERITIES = ("info", "warn", "error")

#: The registered anomaly vocabulary. Every ``note()`` call site in the
#: package must use a kind from this table (``scripts/lint_blocking.py``
#: enforces it at the literal site; ``# kind-ok`` escapes) — free-string
#: kinds fragment the ``counts_by_kind`` rollup and the alert engine's
#: breach vocabulary. Grow the table, don't invent inline.
KINDS = (
    "retrace_storm",
    "heartbeat_flap",
    "worker_dead",
    "stale_notmod",
    "backpressure_reject",
    "deadline_eviction",
    "wal_restore",
    "ps_kill",
    # training-health alert kinds (obs/alerts.py)
    "slo_breach",
    "staleness_spike",
    "worker_lagging",
    # shard-group lifecycle (parameter/group.py)
    "shard_failover",
    "standby_promoted",
    "shard_map_mismatch",
    # goodput / canary plane (obs/slo.py, obs/canary.py)
    "goodput_burn",
    "canary_fail",
    # serving fleet lifecycle + autoscaling (serving/fleet/)
    "replica_drain",
    "replica_restart",
    "fleet_scale",
    # paged KV pool (serving/kv_pool.py): a resident prefix-cache entry
    # was LRU-evicted to free blocks under allocation pressure
    "prefix_evict",
    # bounded-staleness admission (parameter/server.py): a pushed delta
    # exceeded the hard max_staleness bound and was refused outright
    "delta_rejected",
    # durable telemetry store (obs/store.py): a torn segment tail was
    # truncated on warm reopen (predecessor boot died mid-append)
    "store_corrupt_tail",
    # elastic hyperparameter tuner (tune/): successive-halving lifecycle
    # — a trial promoted to the next rung, early-stopped by the halving
    # rule, resumed from its vault checkpoint after a worker death, or
    # flagged by the stall detector as running without progress
    "trial_promoted",
    "trial_pruned",
    "trial_resumed",
    "trial_stalled",
    # speculative serving decode (serving/spec.py): the draft source
    # failed to produce params (e.g. PS pull error) and the decoder
    # degraded to plain decode for that window instead of erroring
    "spec_fallback",
    # per-tenant cost attribution (obs/tenancy.py): a tenant's
    # multi-window goodput burn crossed budget parity, or one tenant
    # holds most of the KV pool's integrated block-seconds while other
    # tenants are also paying for blocks
    "tenant_burn",
    "noisy_neighbor",
    # disaggregated serving (serving/fleet/): a prefill replica's KV
    # blocks handed off to a decode replica (kv_handoff), or the
    # handoff failed and the request degraded to a local re-prefill
    # (tier_handoff_fail); QoS admission throttled a tenant's submit
    # (admission_throttle) or preempted its queued request to seat a
    # higher-priority one (tenant_preempted)
    "kv_handoff",
    "tier_handoff_fail",
    "admission_throttle",
    "tenant_preempted",
    # disagg alert-plane kinds (obs/alerts.py): tier load divergence
    # and handoff-latency p99 breaches
    "tier_imbalance",
    "handoff_slow",
    # live model delivery (rollout/): an engine atomically swapped to
    # newly-pulled serving weights at a decode-step boundary
    # (weight_swap); a subscriber's PS pull failed and the engine kept
    # serving its current weights (weight_pull_fail); the controller
    # promoted a baked canary version fleet-wide (rollout_promote) or
    # rolled the canary back to the pinned prior version
    # (rollout_rollback)
    "weight_swap",
    "weight_pull_fail",
    "rollout_promote",
    "rollout_rollback",
    # rollout alert-plane kinds (obs/alerts.py): a rollout has sat in a
    # non-idle phase past its stuck threshold (rollout_stuck); replicas
    # are serving versions >1 apart past the skew grace window
    # (version_skew)
    "rollout_stuck",
    "version_skew",
)


class FlightEvent:
    """One recorded anomaly."""

    __slots__ = ("kind", "severity", "wall_s", "mono_s", "trace_id",
                 "detail")

    def __init__(self, kind: str, severity: str, wall_s: float,
                 mono_s: float, trace_id: Optional[str],
                 detail: Dict[str, Any]):
        self.kind = kind
        self.severity = severity
        self.wall_s = wall_s
        self.mono_s = mono_s
        self.trace_id = trace_id
        self.detail = detail

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "severity": self.severity,
            "wall_s": self.wall_s,
            "mono_s": self.mono_s,
            "trace_id": self.trace_id,
            "detail": self.detail,
        }

    def __repr__(self):
        return (f"FlightEvent({self.kind!r}, {self.severity}, "
                f"trace={self.trace_id}, {self.detail})")


class FlightRecorder:
    """Bounded anomaly ring.

    ``enabled=False`` makes ``note()`` a single attribute check —
    ``NULL_FLIGHT_RECORDER`` is the shared disabled instance, so
    instrumented code can hold a recorder unconditionally.
    """

    def __init__(self, capacity: int = 1024, enabled: bool = True,
                 clock=time.monotonic):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {capacity}")
        self.capacity = capacity
        self.enabled = enabled
        self.clock = clock
        self.dropped = 0
        self._events: deque = deque(maxlen=capacity)
        self._lock = locksan.make_lock("FlightRecorder._lock")
        self._dropped_counter = None  # lazily bound on first overwrite
        self._stores: tuple = ()  # durable tees (obs/store.py), COW

    def note(self, kind: str, severity: str = "warn",
             **detail) -> Optional[FlightEvent]:
        """Record one anomaly, tagged with the active trace context.

        ``kind`` is a stable snake_case event name (``retrace_storm``,
        ``heartbeat_flap``, ``backpressure_reject``, ...); ``detail``
        holds the site-specific facts (worker id, depth, version delta).
        """
        if not self.enabled:
            return None
        if severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got {severity!r}"
            )
        ctx = _trace.current_context()
        event = FlightEvent(kind, severity, time.time(), self.clock(),
                            ctx.trace_id if ctx is not None else None,
                            detail)
        with self._lock:
            overwrote = len(self._events) == self._events.maxlen
            if overwrote:
                self.dropped += 1
            self._events.append(event)
        # Durable tee: anomalies reach disk at note() time, so a SIGKILL
        # between now and any clean dump loses nothing (obs/store.py).
        # The tuple is copy-on-write — no lock on the hot path; a store
        # must never take a host down with it.
        for store in self._stores:
            try:
                store.record_flight(event)
            except Exception:
                pass
        if overwrote:
            # Silent anomaly loss must itself be observable: mirror the
            # tracer's truncation counter in the process registry so
            # expose_text()/alert rules see it. Lazy-bound outside the
            # ring lock; registry counters take their own lock.
            counter = self._dropped_counter
            if counter is None:
                try:
                    from elephas_tpu import obs

                    counter = obs.default_registry().counter(
                        "flight_dropped_total",
                        help="flight-recorder events overwritten by the "
                             "bounded ring before read-out",
                    )
                except Exception:
                    counter = False  # registry unavailable: stop trying
                self._dropped_counter = counter
            if counter:
                counter.inc()
        return event

    # -- durable tee -------------------------------------------------------

    def attach_store(self, store) -> None:
        """Tee every subsequent ``note()`` into ``store`` (a
        ``TelemetryStore``). Idempotent; multiple co-hosted processes
        may each attach their own store to the shared recorder —
        ``obs/incident.py`` dedupes the copies after the fact."""
        with self._lock:
            if store not in self._stores:
                self._stores = self._stores + (store,)

    def detach_store(self, store) -> None:
        """Stop teeing into ``store`` (unmount/kill path). Idempotent."""
        with self._lock:
            self._stores = tuple(s for s in self._stores if s is not store)

    # -- read-out ----------------------------------------------------------

    def __len__(self) -> int:
        return len(self._events)

    def events(self, kind: Optional[str] = None,
               min_severity: str = "info") -> List[FlightEvent]:
        """Ring snapshot (oldest first), optionally filtered."""
        floor = SEVERITIES.index(min_severity)
        with self._lock:
            out = list(self._events)
        return [e for e in out
                if (kind is None or e.kind == kind)
                and SEVERITIES.index(e.severity) >= floor]

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready dump — the ``/flight`` ops route and ``dump()``
        both serve exactly this."""
        events = self.events()
        counts: Dict[str, int] = {}
        for e in events:
            counts[e.kind] = counts.get(e.kind, 0) + 1
        return {
            "events": [e.to_dict() for e in events],
            "counts_by_kind": counts,
            "dropped": self.dropped,
            "capacity": self.capacity,
        }

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def dump(self, path: str) -> str:
        """Write the ring to ``path`` as JSON (crash-path artifact).
        Returns the path."""
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=1)
        return path


#: Shared disabled instance — hold it unconditionally in instrumented code.
NULL_FLIGHT_RECORDER = FlightRecorder(capacity=0, enabled=False)
