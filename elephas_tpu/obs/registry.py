"""Metrics registry: named counters, gauges, fixed-bucket histograms.

The registry is the scrape surface the ROADMAP's serving north star
needs: in-memory aggregation only (recording a sample is an integer
bump — no allocation, no device sync, safe inside the pipelined
scheduler's overlap window), read out either as a Prometheus-style text
exposition (``expose_text``) or as one structured line through the
existing ``metrics.logging.JsonlSink`` (``log_to`` — the same artifact
format every committed benchmark in this repo uses).

``Histogram`` gives p50/p90/p99 without storing every sample: fixed
bucket bounds (default: a geometric latency ladder from 10µs to ~80s),
percentiles linearly interpolated inside the owning bucket and clamped
to the observed min/max, so the estimate is never wider than one bucket
off the exact quantile (tests pin this against exact quantiles on known
distributions).

Labels (this PR): ``registry.counter("ps_push_retry_total",
labelnames=("worker",))`` returns a ``Family``; ``.labels(worker="w1")``
get-or-creates the child instrument. One metric name, N label-keyed
children — instead of N metric names with the dimension baked in
(``retrace_total::prog``), which Prometheus can neither aggregate nor
relabel. ``Family.value`` sums the children, so "total across the
dimension" reads stay one attribute access. Exposition renders
``name{worker="w1"} 3`` with proper label-value escaping.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Family",
    "MetricsRegistry",
    "default_latency_buckets",
]


def default_latency_buckets() -> Tuple[float, ...]:
    """Geometric ladder 10µs → ~80s (×2 per bucket, 24 bounds): spans a
    sub-ms decode step and a minute-long compile in one histogram."""
    return tuple(1e-5 * 2 ** i for i in range(24))


_trace_mod = None


def _current_trace_id() -> Optional[str]:
    """Active trace id, or None. Lazily binds ``obs.trace`` so the
    registry (imported first by ``obs/__init__``) never participates in
    an import cycle; only exemplar-enabled histograms pay the call."""
    global _trace_mod
    if _trace_mod is None:
        from elephas_tpu.obs import trace as _t
        _trace_mod = _t
    ctx = _trace_mod.current_context()
    return ctx.trace_id if ctx is not None else None


def _escape_label_value(v: str) -> str:
    """Prometheus exposition escaping: backslash, quote, newline."""
    return (v.replace("\\", r"\\").replace('"', r"\"")
             .replace("\n", r"\n"))


def _render_labels(labels: Dict[str, str], **extra: str) -> str:
    """``{k="v",...}`` suffix for a sample line; "" when empty.

    ``extra`` appends synthetic labels (the histogram ``le`` bound)
    after the family's own, matching Prometheus ordering convention.
    """
    items = list(labels.items()) + list(extra.items())
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(str(v))}"'
                    for k, v in items)
    return "{" + body + "}"


class Counter:
    """Monotonic counter (``inc`` only)."""

    __slots__ = ("name", "help", "labels", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.labels = None  # set by the owning Family, if any
        self._value = 0

    def inc(self, n: int = 1) -> None:
        self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "help", "labels", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.labels = None  # set by the owning Family, if any
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram with interpolated percentiles.

    ``buckets`` are ascending upper bounds; an implicit +inf bucket
    catches overflow. Observing is two comparisons + two integer bumps
    (bisect over ~24 bounds); nothing per-sample is stored beyond
    count/sum/min/max, so a million decode steps cost the same memory
    as ten.

    ``exemplars=True`` additionally latches the *active trace id* per
    bucket on every observe (last-writer-wins, one string slot per
    bucket — still O(buckets) memory): a p99 spike in the exposition
    joins directly to the span tree of a request that actually landed
    in that bucket, via ``exemplar_ids()`` and
    ``scripts/trace_report.py``. Off by default; recording sites that
    run under per-request trace context (``ServingMetrics``'s ITL
    mirror) opt in.
    """

    __slots__ = ("name", "help", "labels", "bounds", "counts", "count",
                 "sum", "min", "max", "exemplars")

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Iterable[float]] = None,
                 exemplars: bool = False):
        self.name = name
        self.help = help
        self.labels = None  # set by the owning Family, if any
        bounds = tuple(sorted(buckets)) if buckets is not None \
            else default_latency_buckets()
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # trailing +inf bucket
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.exemplars: Optional[List[Optional[str]]] = \
            [None] * (len(bounds) + 1) if exemplars else None

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        # Hand-rolled bisect_right over a ~24-entry tuple: no imports in
        # the hot path, O(log n) either way.
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.counts[lo] += 1
        if self.exemplars is not None:
            trace_id = _current_trace_id()
            if trace_id is not None:
                self.exemplars[lo] = trace_id

    def exemplar_ids(self) -> Dict[str, str]:
        """``le-bound → trace id`` for every bucket that latched one
        (the join key into a trace dump); empty when disabled."""
        if self.exemplars is None:
            return {}
        out: Dict[str, str] = {}
        for i, tid in enumerate(self.exemplars):
            if tid is not None:
                le = (f"{self.bounds[i]:g}" if i < len(self.bounds)
                      else "+Inf")
                out[le] = tid
        return out

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def percentile(self, q: float) -> Optional[float]:
        """Estimated ``q``-quantile (q in [0, 1]); None when empty.

        Linear interpolation inside the bucket holding the target rank,
        clamped to [observed min, observed max] — so degenerate
        single-bucket data still reports sane numbers.
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.count == 0:
            return None
        rank = q * self.count
        cum = 0.0
        lower = self.min
        for i, c in enumerate(self.counts):
            upper = self.bounds[i] if i < len(self.bounds) else self.max
            if c and cum + c >= rank:
                frac = (rank - cum) / c
                est = lower + (min(upper, self.max) - lower) * frac
                return min(max(est, self.min), self.max)
            cum += c
            if i < len(self.bounds):
                lower = max(self.bounds[i], self.min)
        return self.max

    def percentiles(self, qs=(0.50, 0.95, 0.99)) -> Dict[str, Optional[float]]:
        """``{"p50": ..., "p95": ..., "p99": ...}`` convenience dict."""
        return {f"p{int(q * 100)}": self.percentile(q) for q in qs}


class Family:
    """A labeled metric: one name, one label schema, N children keyed by
    label values. ``family.labels(worker="w1")`` get-or-creates the
    child instrument (a plain Counter/Gauge/Histogram whose ``labels``
    attr holds the key→value dict the exposition renders).

    ``value`` sums the children (counters/gauges), so call sites that
    read "the total across the dimension" don't need to enumerate.
    """

    __slots__ = ("name", "help", "cls", "labelnames", "_kw",
                 "_children", "_lock")

    def __init__(self, cls, name: str, help: str,
                 labelnames: Tuple[str, ...], **kw):
        if not labelnames:
            raise ValueError("Family needs at least one label name")
        self.name = name
        self.help = help
        self.cls = cls
        self.labelnames = tuple(labelnames)
        self._kw = kw
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(sorted(kv))}"
            )
        key = tuple(str(kv[k]) for k in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self.cls(self.name, help=self.help, **self._kw)
                    child.labels = dict(zip(self.labelnames, key))
                    self._children[key] = child
        return child

    def children(self) -> List[object]:
        """Children sorted by label values (stable exposition order)."""
        with self._lock:
            return [self._children[k] for k in sorted(self._children)]

    @property
    def value(self):
        """Sum across children (counter/gauge families)."""
        return sum(c.value for c in self.children())


class MetricsRegistry:
    """Name → instrument map with get-or-create accessors.

    Accessors are idempotent (same name returns the same instrument) and
    kind-checked — registering ``"x"`` as both a counter and a gauge, or
    as both plain and labeled (or with two different label schemas), is
    a programming error worth failing loudly on.
    """

    def __init__(self):
        self._instruments: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Tuple[str, ...] = (), **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                if labelnames:
                    inst = Family(cls, name, help, tuple(labelnames), **kw)
                else:
                    inst = cls(name, help=help, **kw)
                self._instruments[name] = inst
            elif isinstance(inst, Family):
                if inst.cls is not cls:
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{inst.cls.__name__} family, not {cls.__name__}"
                    )
                if tuple(labelnames) != inst.labelnames:
                    raise TypeError(
                        f"metric {name!r} already registered with labels "
                        f"{inst.labelnames}, not {tuple(labelnames)}"
                    )
            elif labelnames:
                raise TypeError(
                    f"metric {name!r} already registered unlabeled; "
                    f"cannot re-register with labels {tuple(labelnames)}"
                )
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}"
                )
            return inst

    def counter(self, name: str, help: str = "",
                labelnames: Tuple[str, ...] = ()):
        return self._get_or_create(Counter, name, help,
                                   labelnames=labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Tuple[str, ...] = ()):
        return self._get_or_create(Gauge, name, help, labelnames=labelnames)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Iterable[float]] = None,
                  labelnames: Tuple[str, ...] = (),
                  exemplars: bool = False):
        return self._get_or_create(Histogram, name, help,
                                   labelnames=labelnames, buckets=buckets,
                                   exemplars=exemplars)

    def instruments(self) -> List[object]:
        with self._lock:
            return [self._instruments[k] for k in sorted(self._instruments)]

    def clear(self) -> None:
        with self._lock:
            self._instruments.clear()

    # -- readout -----------------------------------------------------------

    def expose_text(self) -> str:
        """Prometheus-style text exposition (scrape/dump surface).

        Labeled families emit one HELP/TYPE header and one sample line
        per child (``name{worker="w1"} 3``); labeled histograms merge
        the family labels with ``le`` on every bucket line.
        """
        lines: List[str] = []
        for inst in self.instruments():
            if isinstance(inst, Family):
                kind = inst.cls.__name__.lower()
                children = inst.children()
            else:
                kind = type(inst).__name__.lower()
                children = [inst]
            if inst.help:
                lines.append(f"# HELP {inst.name} {inst.help}")
            lines.append(f"# TYPE {inst.name} {kind}")
            for child in children:
                labels = child.labels or {}
                if isinstance(child, Histogram):
                    cum = 0
                    for bound, c in zip(child.bounds, child.counts):
                        cum += c
                        lines.append(
                            f"{child.name}_bucket"
                            f"{_render_labels(labels, le=f'{bound:g}')}"
                            f" {cum}"
                        )
                    lines.append(
                        f"{child.name}_bucket"
                        f"{_render_labels(labels, le='+Inf')} {child.count}"
                    )
                    suffix = _render_labels(labels)
                    lines.append(f"{child.name}_sum{suffix} {child.sum:g}")
                    lines.append(f"{child.name}_count{suffix} {child.count}")
                else:
                    lines.append(
                        f"{child.name}{_render_labels(labels)} "
                        f"{child.value:g}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, float]:
        """Flat name → number dict; histograms expand to
        ``_count``/``_sum``/``_p50``/``_p95``/``_p99``; labeled children
        key as ``name{worker="w1"}``."""
        out: Dict[str, float] = {}

        def emit(child):
            suffix = _render_labels(child.labels or {})
            if isinstance(child, Histogram):
                out[f"{child.name}_count{suffix}"] = child.count
                out[f"{child.name}_sum{suffix}"] = child.sum
                for pk, v in child.percentiles().items():
                    if v is not None:
                        out[f"{child.name}_{pk}{suffix}"] = v
            else:
                out[f"{child.name}{suffix}"] = child.value

        for inst in self.instruments():
            if isinstance(inst, Family):
                for child in inst.children():
                    emit(child)
            else:
                emit(inst)
        return out

    def exemplars(self) -> Dict[str, Dict[str, str]]:
        """Every latched histogram exemplar: snapshot-style key
        (``name`` or ``name{labels}``) → ``{le: trace_id}``. Served
        out-of-band from the text exposition (the 0.0.4 format has no
        exemplar syntax and ``obs.fleet.parse_prometheus_text`` must
        keep round-tripping ``expose_text`` unchanged)."""
        out: Dict[str, Dict[str, str]] = {}

        def emit(child):
            if isinstance(child, Histogram):
                ids = child.exemplar_ids()
                if ids:
                    out[f"{child.name}"
                        f"{_render_labels(child.labels or {})}"] = ids

        for inst in self.instruments():
            if isinstance(inst, Family):
                for child in inst.children():
                    emit(child)
            else:
                emit(inst)
        return out

    def log_to(self, sink, step: int = 0, **extra) -> None:
        """One structured line into a ``metrics.logging.JsonlSink``
        (duck-typed: anything with ``log(step, **metrics)``)."""
        sink.log(step, event="metrics", **{**self.snapshot(), **extra})
