"""Metrics registry: named counters, gauges, fixed-bucket histograms.

The registry is the scrape surface the ROADMAP's serving north star
needs: in-memory aggregation only (recording a sample is an integer
bump — no allocation, no device sync, safe inside the pipelined
scheduler's overlap window), read out either as a Prometheus-style text
exposition (``expose_text``) or as one structured line through the
existing ``metrics.logging.JsonlSink`` (``log_to`` — the same artifact
format every committed benchmark in this repo uses).

``Histogram`` gives p50/p90/p99 without storing every sample: fixed
bucket bounds (default: a geometric latency ladder from 10µs to ~80s),
percentiles linearly interpolated inside the owning bucket and clamped
to the observed min/max, so the estimate is never wider than one bucket
off the exact quantile (tests pin this against exact quantiles on known
distributions).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_latency_buckets",
]


def default_latency_buckets() -> Tuple[float, ...]:
    """Geometric ladder 10µs → ~80s (×2 per bucket, 24 bounds): spans a
    sub-ms decode step and a minute-long compile in one histogram."""
    return tuple(1e-5 * 2 ** i for i in range(24))


class Counter:
    """Monotonic counter (``inc`` only)."""

    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0

    def inc(self, n: int = 1) -> None:
        self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram with interpolated percentiles.

    ``buckets`` are ascending upper bounds; an implicit +inf bucket
    catches overflow. Observing is two comparisons + two integer bumps
    (bisect over ~24 bounds); nothing per-sample is stored beyond
    count/sum/min/max, so a million decode steps cost the same memory
    as ten.
    """

    __slots__ = ("name", "help", "bounds", "counts", "count", "sum",
                 "min", "max")

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Iterable[float]] = None):
        self.name = name
        self.help = help
        bounds = tuple(sorted(buckets)) if buckets is not None \
            else default_latency_buckets()
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # trailing +inf bucket
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        # Hand-rolled bisect_right over a ~24-entry tuple: no imports in
        # the hot path, O(log n) either way.
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.counts[lo] += 1

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def percentile(self, q: float) -> Optional[float]:
        """Estimated ``q``-quantile (q in [0, 1]); None when empty.

        Linear interpolation inside the bucket holding the target rank,
        clamped to [observed min, observed max] — so degenerate
        single-bucket data still reports sane numbers.
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.count == 0:
            return None
        rank = q * self.count
        cum = 0.0
        lower = self.min
        for i, c in enumerate(self.counts):
            upper = self.bounds[i] if i < len(self.bounds) else self.max
            if c and cum + c >= rank:
                frac = (rank - cum) / c
                est = lower + (min(upper, self.max) - lower) * frac
                return min(max(est, self.min), self.max)
            cum += c
            if i < len(self.bounds):
                lower = max(self.bounds[i], self.min)
        return self.max

    def percentiles(self, qs=(0.50, 0.95, 0.99)) -> Dict[str, Optional[float]]:
        """``{"p50": ..., "p95": ..., "p99": ...}`` convenience dict."""
        return {f"p{int(q * 100)}": self.percentile(q) for q in qs}


class MetricsRegistry:
    """Name → instrument map with get-or-create accessors.

    Accessors are idempotent (same name returns the same instrument) and
    kind-checked — registering ``"x"`` as both a counter and a gauge is
    a programming error worth failing loudly on.
    """

    def __init__(self):
        self._instruments: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help=help, **kw)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}"
                )
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def instruments(self) -> List[object]:
        with self._lock:
            return [self._instruments[k] for k in sorted(self._instruments)]

    def clear(self) -> None:
        with self._lock:
            self._instruments.clear()

    # -- readout -----------------------------------------------------------

    def expose_text(self) -> str:
        """Prometheus-style text exposition (scrape/dump surface)."""
        lines: List[str] = []
        for inst in self.instruments():
            kind = type(inst).__name__.lower()
            if inst.help:
                lines.append(f"# HELP {inst.name} {inst.help}")
            lines.append(f"# TYPE {inst.name} {kind}")
            if isinstance(inst, Histogram):
                cum = 0
                for bound, c in zip(inst.bounds, inst.counts):
                    cum += c
                    lines.append(
                        f'{inst.name}_bucket{{le="{bound:g}"}} {cum}'
                    )
                lines.append(
                    f'{inst.name}_bucket{{le="+Inf"}} {inst.count}'
                )
                lines.append(f"{inst.name}_sum {inst.sum:g}")
                lines.append(f"{inst.name}_count {inst.count}")
            else:
                lines.append(f"{inst.name} {inst.value:g}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, float]:
        """Flat name → number dict; histograms expand to
        ``_count``/``_sum``/``_p50``/``_p95``/``_p99``."""
        out: Dict[str, float] = {}
        for inst in self.instruments():
            if isinstance(inst, Histogram):
                out[f"{inst.name}_count"] = inst.count
                out[f"{inst.name}_sum"] = inst.sum
                for key, v in inst.percentiles().items():
                    if v is not None:
                        out[f"{inst.name}_{key}"] = v
            else:
                out[inst.name] = inst.value
        return out

    def log_to(self, sink, step: int = 0, **extra) -> None:
        """One structured line into a ``metrics.logging.JsonlSink``
        (duck-typed: anything with ``log(step, **metrics)``)."""
        sink.log(step, event="metrics", **{**self.snapshot(), **extra})
