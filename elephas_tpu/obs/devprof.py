"""On-demand device profiling: trace capture + memory watermarks.

The ROADMAP's "serving on a real chip" item needs two hooks preinstalled
before any TPU shows up, and both are useful on CPU today:

- ``DeviceProfiler`` — a start/stop bridge over ``jax.profiler``'s
  trace capture, guarded by a non-blocking capture lock (XLA allows one
  active capture per process; a second ``start`` answers *busy* instead
  of corrupting the first). Dumps land next to the WAL when mounted on
  a PS (same placement as the kill-path flight dump — one directory
  holds everything needed to debug an incarnation), or in a temp dir
  otherwise. The opsd ``/profile`` route drives it remotely:
  ``?action=start`` / ``?action=stop`` / bare GET for status.
- ``device_memory_snapshot`` / ``record_device_memory`` — per-device
  live-buffer byte watermarks surfaced as ``device_mem_bytes{device=}``
  gauges and sampled into the history ring. Backends differ wildly
  here: TPU/GPU runtimes answer ``device.memory_stats()``, CPU usually
  answers ``None`` — so the probe tries ``memory_stats``, falls back to
  summing ``live_buffers()`` sizes, and reports nothing rather than
  guessing. Every probe is exception-guarded: a broken runtime query
  must never take down the sampler thread driving it.

The profiler's starter/stopper are injectable so tests exercise the
lock protocol and dump lifecycle without importing jax at all.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
import warnings
from typing import Callable, Dict, Optional

__all__ = [
    "DeviceProfiler",
    "device_memory_snapshot",
    "record_device_memory",
]


def _jax_start_trace(out_dir: str) -> None:
    import jax

    jax.profiler.start_trace(out_dir)


def _jax_stop_trace() -> None:
    import jax

    jax.profiler.stop_trace()


class DeviceProfiler:
    """Start/stop trace capture with a capture lock (see module doc).

    ``start`` answers ``{"status": "started", ...}`` or
    ``{"status": "busy", ...}`` — never raises for the already-capturing
    case, because the remote caller poking ``/profile?action=start``
    twice deserves a 409-shaped answer, not a stack trace. Runtime
    failures from the underlying profiler *are* surfaced (as
    ``{"status": "error", ...}``) so a misconfigured backend is visible.
    """

    def __init__(self, out_dir: Optional[str] = None,
                 starter: Callable[[str], None] = _jax_start_trace,
                 stopper: Callable[[], None] = _jax_stop_trace,
                 clock=time.monotonic):
        self.out_dir = out_dir
        self._starter = starter
        self._stopper = stopper
        self.clock = clock
        self._lock = threading.Lock()
        self._capturing = False
        self._capture_dir: Optional[str] = None
        self._started_at: Optional[float] = None
        self.captures = 0  # completed start→stop cycles

    def _resolve_dir(self, out_dir: Optional[str]) -> str:
        d = out_dir or self.out_dir
        if d is None:
            d = os.path.join(tempfile.gettempdir(), "elephas-profile")
        os.makedirs(d, exist_ok=True)
        return d

    def start(self, out_dir: Optional[str] = None) -> Dict[str, object]:
        with self._lock:
            if self._capturing:
                return {"status": "busy", "dir": self._capture_dir,
                        "since_s": self.clock() - self._started_at}
            d = self._resolve_dir(out_dir)
            try:
                self._starter(d)
            except Exception as exc:
                return {"status": "error", "error": repr(exc), "dir": d}
            self._capturing = True
            self._capture_dir = d
            self._started_at = self.clock()
            return {"status": "started", "dir": d}

    def stop(self) -> Dict[str, object]:
        with self._lock:
            if not self._capturing:
                return {"status": "idle"}
            d, t0 = self._capture_dir, self._started_at
            try:
                self._stopper()
            except Exception as exc:
                # The capture is unrecoverable either way; release the
                # lock so a retry can start fresh.
                self._capturing = False
                self._capture_dir = None
                self._started_at = None
                return {"status": "error", "error": repr(exc), "dir": d}
            self._capturing = False
            self._capture_dir = None
            self._started_at = None
            self.captures += 1
            return {"status": "stopped", "dir": d,
                    "duration_s": self.clock() - t0}

    def status(self) -> Dict[str, object]:
        with self._lock:
            doc: Dict[str, object] = {
                "capturing": self._capturing,
                "captures": self.captures,
                "dir": self._capture_dir or self.out_dir,
            }
            if self._capturing:
                doc["since_s"] = self.clock() - self._started_at
            return doc


def device_memory_snapshot() -> Dict[str, int]:
    """Per-device live bytes: ``{"TFRT_CPU_0": 123456, ...}``.

    Tries ``device.memory_stats()["bytes_in_use"]`` (TPU/GPU runtimes),
    falls back to summing ``live_buffers()`` sizes (works on CPU in
    current jaxlib), and silently skips devices that answer neither —
    an empty dict is an honest answer on an uninstrumented backend.
    """
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return {}
    out: Dict[str, int] = {}
    for d in devices:
        name = f"{d.platform}_{d.id}"
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_in_use" in stats:
            out[name] = int(stats["bytes_in_use"])
            continue
        try:
            with warnings.catch_warnings():
                # jaxlib deprecates per-device live_buffers() but it is
                # the only per-DEVICE attribution CPU offers today;
                # don't let every scrape print the notice.
                warnings.simplefilter("ignore", DeprecationWarning)
                out[name] = sum(int(b.nbytes) for b in d.live_buffers())
        except Exception:
            continue
    return out


def record_device_memory(registry=None) -> Dict[str, int]:
    """Probe device memory and set ``device_mem_bytes{device=}`` gauges.

    This is the ``extra_fn`` a ``HistorySampler`` runs before each tick,
    so the watermarks are fresh in the snapshot the tick records. Returns
    the probe result (handy for the ``/profile`` status body).
    """
    if registry is None:
        from elephas_tpu import obs

        registry = obs.default_registry()
    snap = device_memory_snapshot()
    if snap:
        gauge = registry.gauge(
            "device_mem_bytes",
            help="live device buffer bytes, by device",
            labelnames=("device",))
        for name, nbytes in snap.items():
            gauge.labels(device=name).set(nbytes)
    return snap
