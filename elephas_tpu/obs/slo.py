"""Goodput accounting: did users get what they asked for, per window.

Throughput says how many tokens the engine moved; it says nothing
about whether requests met their latency promises. This module keeps
the second ledger — *goodput*, the fraction of finished requests that
met each declared objective — the way SRE burn-rate alerting expects
it:

- ``SLOObjective`` — a declarative promise evaluated against one
  finished ``GenerationResult``: time-to-first-token under a bound,
  mean inter-token latency under a bound, or plain deadline attainment
  (the request completed rather than timing out). Each carries a
  ``target`` (e.g. 0.99) whose complement is the error budget.
- ``GoodputLedger`` — evaluates every finished request against the
  pack, pushes met/missed samples into one ``HistoryRing`` per
  objective, and answers windowed ratios (met/total) over a *fast* and
  a *slow* window plus lifetime counts. The opsd ``/slo`` route serves
  ``snapshot()``.
- Multi-window burn rate — per objective,
  ``burn = min(bad_fast, bad_slow) / (1 - target)``: the classic
  fast+slow AND-gate collapsed into one number (both windows must be
  burning for the minimum to rise). The ledger mirrors it into the
  default registry as ``serving_goodput_burn{objective=}``, and the
  default alert pack (``obs.alerts.default_rules``) carries
  latch-until-clean rules over that family — a warn at budget parity
  and an error at 6x, the ``goodput_burn`` flight kind.

Canary probes never reach this ledger: the engine routes results whose
request ids are canary-tagged to the canary driver instead (see
``obs/canary.py``), so real-traffic goodput is identical with canaries
on or off — pinned by test.

Everything runs on the injected clock; replaying a seeded request
trace replays the exact same ratios and burn values.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from elephas_tpu.obs.history import HistoryRing

OBJECTIVE_KINDS = ("ttft", "itl", "deadline")

DEFAULT_FAST_WINDOW_S = 60.0
DEFAULT_SLOW_WINDOW_S = 600.0

# Burn thresholds the default alert pack keys on: 1.0 means "spending
# budget exactly as fast as the target allows"; 6.0 is the classic
# page-level fast burn.
BURN_WARN = 1.0
BURN_CRITICAL = 6.0


class SLOObjective:
    """One declarative promise about a finished request."""

    __slots__ = ("name", "kind", "threshold_s", "target", "description")

    def __init__(self, name: str, kind: str, *, threshold_s: Optional[float]
                 = None, target: float = 0.99, description: str = ""):
        if kind not in OBJECTIVE_KINDS:
            raise ValueError(
                f"kind must be one of {OBJECTIVE_KINDS}, got {kind!r}")
        if kind != "deadline" and threshold_s is None:
            raise ValueError(f"{kind!r} objective needs threshold_s")
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        self.name = name
        self.kind = kind
        self.threshold_s = None if threshold_s is None else float(threshold_s)
        self.target = float(target)
        self.description = description

    @property
    def budget(self) -> float:
        """Tolerable bad fraction: the complement of the target."""
        return 1.0 - self.target

    def met(self, result) -> bool:
        """Did this finished request keep the promise?

        A request that timed out (or never produced a first token)
        misses every latency objective — "we never answered" is the
        worst latency, not a vacuous pass.
        """
        if self.kind == "deadline":
            return result.status == "completed"
        if result.status != "completed":
            return False
        if self.kind == "ttft":
            return result.ttft_s is not None and \
                result.ttft_s <= self.threshold_s
        # kind == "itl": a single-token answer has no inter-token gaps
        # to violate the bound.
        return result.itl_s_avg is None or \
            result.itl_s_avg <= self.threshold_s

    def to_dict(self) -> Dict[str, object]:
        return {
            "name": self.name, "kind": self.kind,
            "threshold_s": self.threshold_s, "target": self.target,
            "description": self.description,
        }


def default_objectives() -> List[SLOObjective]:
    """The stock serving pack: first token fast, stream smooth, answer
    delivered. Thresholds match the existing ``serving_itl_p99_high``
    alert's working point."""
    return [
        SLOObjective("ttft", "ttft", threshold_s=2.5, target=0.99,
                     description="first token within 2.5 s"),
        SLOObjective("itl_p99", "itl", threshold_s=0.25, target=0.99,
                     description="mean inter-token latency under 250 ms"),
        SLOObjective("deadline", "deadline", target=0.995,
                     description="request completed before its deadline"),
    ]


class GoodputLedger:
    """Windowed met/total accounting over a pack of objectives."""

    def __init__(self, objectives: Optional[Sequence[SLOObjective]] = None,
                 *, fast_window_s: float = DEFAULT_FAST_WINDOW_S,
                 slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
                 clock: Callable[[], float] = time.monotonic,
                 capacity: int = 2048, registry=None):
        if fast_window_s >= slow_window_s:
            raise ValueError("fast window must be shorter than slow window")
        self.objectives = list(default_objectives() if objectives is None
                               else objectives)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.clock = clock
        # registry=None → the process default, resolved lazily on first
        # record (an explicit one keeps seeded ladders self-contained).
        self._registry = registry
        self._lock = threading.Lock()
        self._rings = {o.name: HistoryRing(capacity) for o in self.objectives}
        self._evaluated = 0
        self._met = {o.name: 0 for o in self.objectives}
        self._burn_gauge = None   # lazy family; False after failed bind
        self._ratio_gauge = None

    # -- registry mirror ---------------------------------------------------

    def _families(self):
        if self._burn_gauge is None:
            try:
                reg = self._registry
                if reg is None:
                    from elephas_tpu import obs
                    reg = obs.default_registry()
                self._burn_gauge = reg.gauge(
                    "serving_goodput_burn",
                    help="multi-window SLO burn rate (min of fast/slow bad "
                         "fraction over error budget)",
                    labelnames=("objective",),
                )
                self._ratio_gauge = reg.gauge(
                    "serving_goodput_ratio",
                    help="fast-window goodput ratio (met/total)",
                    labelnames=("objective",),
                )
            except Exception:
                self._burn_gauge = False
                self._ratio_gauge = False
        return self._burn_gauge, self._ratio_gauge

    # -- accounting --------------------------------------------------------

    def record(self, result, now: Optional[float] = None) -> Dict[str, bool]:
        """Evaluate one finished request against every objective."""
        now = self.clock() if now is None else float(now)
        verdicts = {o.name: o.met(result) for o in self.objectives}
        with self._lock:
            self._evaluated += 1
            for name, ok in verdicts.items():
                self._rings[name].push(now, 1.0 if ok else 0.0)
                if ok:
                    self._met[name] += 1
        burn_gauge, ratio_gauge = self._families()
        if burn_gauge:
            burns = self.burn(now=now)
            fast = self.goodput(self.fast_window_s, now=now)
            for o in self.objectives:
                if burns[o.name] is not None:
                    burn_gauge.labels(objective=o.name).set(burns[o.name])
                if fast[o.name] is not None:
                    ratio_gauge.labels(objective=o.name).set(fast[o.name])
        return verdicts

    def _window_ratio(self, ring: HistoryRing, window_s: float,
                      now: float) -> Optional[float]:
        pts = ring.samples(window_s, now=now)
        if not pts:
            return None
        return sum(v for _, v in pts) / len(pts)

    def goodput(self, window_s: Optional[float] = None,
                now: Optional[float] = None) -> Dict[str, Optional[float]]:
        """Per-objective met/total ratio; lifetime when ``window_s`` is
        None; ``None`` entries mean no finished requests in window."""
        now = self.clock() if now is None else float(now)
        with self._lock:
            if window_s is None:
                if self._evaluated == 0:
                    return {o.name: None for o in self.objectives}
                return {o.name: self._met[o.name] / self._evaluated
                        for o in self.objectives}
            return {o.name: self._window_ratio(self._rings[o.name],
                                               window_s, now)
                    for o in self.objectives}

    def burn(self, now: Optional[float] = None) -> Dict[str, Optional[float]]:
        """Multi-window burn per objective: both windows must be bad for
        the minimum to rise, so a brief spike (fast-only) or an old,
        resolved incident (slow-only) reads as no burn."""
        now = self.clock() if now is None else float(now)
        fast = self.goodput(self.fast_window_s, now=now)
        slow = self.goodput(self.slow_window_s, now=now)
        out: Dict[str, Optional[float]] = {}
        for o in self.objectives:
            if fast[o.name] is None or slow[o.name] is None:
                out[o.name] = None
                continue
            bad = min(1.0 - fast[o.name], 1.0 - slow[o.name])
            out[o.name] = bad / o.budget
        return out

    def snapshot(self, now: Optional[float] = None) -> Dict[str, object]:
        """The opsd ``/slo`` document."""
        now = self.clock() if now is None else float(now)
        lifetime = self.goodput(None, now=now)
        defined = [v for v in lifetime.values() if v is not None]
        with self._lock:
            evaluated = self._evaluated
        return {
            "objectives": [o.to_dict() for o in self.objectives],
            "evaluated": evaluated,
            "windows": {"fast_s": self.fast_window_s,
                        "slow_s": self.slow_window_s},
            "goodput": {
                "lifetime": lifetime,
                "fast": self.goodput(self.fast_window_s, now=now),
                "slow": self.goodput(self.slow_window_s, now=now),
            },
            "burn": self.burn(now=now),
            # The single roll-up fleet_top renders: the worst lifetime
            # objective, or None before any traffic.
            "goodput_ratio": min(defined) if defined else None,
        }
