"""SLO alert engine: declarative rules over ``MetricsRegistry`` snapshots.

PR 6's flight recorder captures anomalies only where code *already*
detects them; this module closes the loop by turning any registry
metric into an anomaly source. A rule is declarative data — metric
name, comparator + threshold, evaluation mode (instantaneous value or
windowed rate), severity, and a burn count — and the engine evaluates
the whole pack against ``registry.snapshot()`` on demand (every
``/alerts`` scrape, every bench checkpoint): no background thread, an
injectable clock, so seeded chaos runs replay the exact same ordered
alert sequence.

Matching is per *snapshot key*: a rule on ``ps_staleness_versions_p95``
evaluates every labeled child (``...{worker="w1"}``) independently, so
one rule yields per-worker breaches — that is how ``worker_lagging``
singles out the straggler. Breaches emit FlightRecorder events (kinds
from the registered ``flight.KINDS`` table), bump
``alerts_fired_total{rule=}``, and append to an ordered ``fired``
history the ``/alerts`` route serves.

Rule *names* come from the ``RULE_NAMES`` registered-constant table —
``scripts/lint_blocking.py`` rejects free-string names at ``AlertRule``
call sites (``# kind-ok`` escapes) so dashboards and runbooks can key
on a closed vocabulary.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from elephas_tpu.obs.flight import KINDS
from elephas_tpu.obs.history import HistoryRing
from elephas_tpu.utils import locksan

__all__ = ["AlertEngine", "AlertRule", "RULE_NAMES", "default_rules"]

#: Registered rule-name vocabulary (see module docstring). Grow the
#: table when adding a rule; don't invent names inline.
RULE_NAMES = (
    "staleness_p95_high",
    "worker_lag_high",
    "worker_expiry_rate",
    "push_retry_rate",
    "serving_itl_p99_high",
    "shard_failover_rate",
    "goodput_burn_high",
    "goodput_burn_critical",
    "canary_probe_failures",
    "staleness_rejection_rate",
    "tune_trial_stalled",
    "tenant_burn_high",
    "noisy_neighbor",
    "tier_imbalance",
    "handoff_slow",
    "rollout_stuck",
    "version_skew",
)

_PREDICATES = (">", "<")
_MODES = ("value", "rate")


class AlertRule:
    """One declarative SLO rule.

    ``metric`` names a snapshot key — matched exactly, or as the family
    prefix of labeled keys (``metric{...}``). ``mode="value"`` compares
    the key's current value; ``mode="rate"`` compares its per-second
    rate of change over the trailing ``window_s`` (needs two evaluation
    points inside the window before it can trip — counters only).
    ``burn`` is how many *consecutive* evaluations must trip before the
    breach fires; after firing, the rule re-arms once it evaluates
    clean.
    """

    __slots__ = ("name", "metric", "predicate", "threshold", "window_s",
                 "mode", "severity", "burn", "kind")

    def __init__(self, name: str, metric: str, predicate: str,
                 threshold: float, kind: str, window_s: float = 60.0,
                 mode: str = "value", severity: str = "warn",
                 burn: int = 1):
        if predicate not in _PREDICATES:
            raise ValueError(
                f"predicate must be one of {_PREDICATES}, got {predicate!r}")
        if mode not in _MODES:
            raise ValueError(f"mode must be one of {_MODES}, got {mode!r}")
        if kind not in KINDS:
            raise ValueError(
                f"alert kind must come from flight.KINDS, got {kind!r}")
        if burn < 1:
            raise ValueError(f"burn must be >= 1, got {burn}")
        self.name = name
        self.metric = metric
        self.predicate = predicate
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.mode = mode
        self.severity = severity
        self.burn = int(burn)
        self.kind = kind

    def to_dict(self) -> Dict[str, Any]:
        return {s: getattr(self, s) for s in self.__slots__}

    def __repr__(self):
        return (f"AlertRule({self.name!r}, {self.metric!r} "
                f"{self.predicate} {self.threshold}, mode={self.mode}, "
                f"burn={self.burn}, kind={self.kind!r})")


def default_rules() -> List[AlertRule]:
    """The stock training-health pack. Thresholds are deliberately
    conservative defaults — override by constructing the engine with an
    explicit rule list."""
    return [
        # Applied-delta staleness: p95 of any worker's version lag.
        AlertRule("staleness_p95_high", "ps_staleness_versions_p95",
                  ">", 8.0, kind="staleness_spike", severity="warn"),
        # A single worker far behind the fleet (same family, harder
        # threshold): the bounded-staleness admission candidate.
        AlertRule("worker_lag_high", "ps_staleness_versions_p95",
                  ">", 32.0, kind="worker_lagging", severity="error"),
        # Membership churn: liveness expiries per second.
        AlertRule("worker_expiry_rate", "ps_worker_expired_total",
                  ">", 0.1, kind="slo_breach", mode="rate",
                  window_s=60.0, severity="warn", burn=2),
        # Push retries per second (comms pipeline under partition/loss).
        AlertRule("push_retry_rate", "ps_push_retry_total",
                  ">", 0.5, kind="slo_breach", mode="rate",
                  window_s=60.0, severity="warn", burn=2),
        # Bounded-staleness admission refusing deltas at a sustained
        # rate: an occasional rejection is the ratchet doing its job
        # (the worker halves its push interval and recovers); a
        # sustained rate means some worker can't catch up and its
        # training work is being thrown away.
        AlertRule("staleness_rejection_rate", "ps_delta_rejected_total",
                  ">", 0.2, kind="delta_rejected", mode="rate",
                  window_s=60.0, severity="warn", burn=2),
        # Serving inter-token latency p99 (seconds).
        AlertRule("serving_itl_p99_high", "serving_itl_seconds_p99",
                  ">", 0.25, kind="slo_breach", severity="warn"),
        # PS-group standby promotions per second: one failover is the
        # mechanism working; a sustained rate means primaries are
        # flapping (or the detector threshold is mis-set) and each
        # promotion burns the shard's only spare.
        AlertRule("shard_failover_rate", "ps_shard_failover_total",
                  ">", 1 / 300.0, kind="shard_failover", mode="rate",
                  window_s=600.0, severity="error"),
        # Multi-window SLO burn (obs/slo.py mirrors
        # serving_goodput_burn{objective=} = min(fast, slow bad
        # fraction) / budget — both windows must be burning for the
        # gauge to rise, so these are the classic fast+slow AND-gate
        # as plain value rules, latch-until-clean like every rule).
        # Warn at budget parity, page at the 6x fast burn.
        AlertRule("goodput_burn_high", "serving_goodput_burn",
                  ">", 1.0, kind="goodput_burn", severity="warn"),
        AlertRule("goodput_burn_critical", "serving_goodput_burn",
                  ">", 6.0, kind="goodput_burn", severity="error"),
        # Blackbox canary probes failing at any sustained rate: users
        # (or workers) cannot get through regardless of what the
        # whitebox metrics claim.
        AlertRule("canary_probe_failures", "serving_canary_fail_total",
                  ">", 0.0, kind="canary_fail", mode="rate",
                  window_s=60.0, severity="error"),
        # Elastic tuner: the slowest RUNNING trial has not progressed
        # for two minutes. The gauge is refreshed at every unit
        # boundary by the tune runner; a trial wedged in a device call
        # can't refresh it down, which is exactly the point — the
        # elastic pool's detector will expire the worker, and this rule
        # is the operator-facing heads-up that a re-lease is coming.
        AlertRule("tune_trial_stalled", "tune_trial_stall_seconds",
                  ">", 120.0, kind="trial_stalled", severity="warn"),
        # Disaggregated serving topology: the prefill and decode tiers'
        # average load scores diverging past half the scale means one
        # tier is starved while the other saturates — rebalance the
        # tier split (the gauge is 0 on a mono fleet, so the rule
        # idles). Handoff p99 creeping toward decode-ITL territory
        # erodes the entire point of tiering — the export/import path
        # should be microseconds of staging, not a scheduling stall.
        AlertRule("tier_imbalance", "fleet_tier_imbalance",
                  ">", 0.5, kind="tier_imbalance", severity="warn"),
        AlertRule("handoff_slow", "fleet_handoff_seconds_p99",
                  ">", 0.25, kind="handoff_slow", severity="warn"),
        # Live model delivery: a rollout that has sat in a non-idle
        # phase for minutes is wedged — the canary is neither being
        # judged good (promote) nor bad (rollback), usually a dead
        # canary replica or a judge starved of traffic. Version skew
        # means replicas are serving models >1 version apart after the
        # promotion ripple should have converged — mixed-fleet answers
        # are a correctness smell, not just an ops one. Both gauges are
        # refreshed by the RolloutController's tick and sit at 0 on
        # fleets without one, so the rules idle elsewhere.
        AlertRule("rollout_stuck", "fleet_rollout_age_s",
                  ">", 120.0, kind="rollout_stuck", severity="warn"),
        AlertRule("version_skew", "fleet_version_skew",
                  ">", 1.0, kind="version_skew", severity="warn",
                  burn=2),
    ]


class AlertEngine:
    """Evaluates a rule pack against registry snapshots (thread-safe).

    ``evaluate()`` is the only mutation point and is explicitly driven —
    by the ``/alerts`` scrape, by bench checkpoints, by tests — on an
    injectable clock, so there is nothing time-racy to make a seeded
    chaos run non-deterministic. Missing metrics idle their rules (a
    serving rule on a PS process never errors, it just never trips).
    """

    def __init__(self, registry=None, flight=None,
                 rules: Optional[List[AlertRule]] = None,
                 clock=time.monotonic):
        self._registry = registry
        self._flight = flight
        self.rules = list(rules) if rules is not None else default_rules()
        self.clock = clock
        self._lock = locksan.make_lock("AlertEngine._lock")
        # (rule.name, key) → consecutive trip count / latched breach.
        self._trips: Dict[Tuple[str, str], int] = {}
        self._breached: Dict[Tuple[str, str], bool] = {}
        # (rule.name, key) → HistoryRing for rate rules: the same
        # windowed-rate substrate /history serves, instead of a private
        # two-point-delta bookkeeping scheme.
        self._points: Dict[Tuple[str, str], HistoryRing] = {}
        self.fired: List[Dict[str, Any]] = []
        self._stores: tuple = ()  # durable tees (obs/store.py), COW

    # -- surface resolution (late, so process globals rebind) ---------------

    def _get_registry(self):
        if self._registry is not None:
            return self._registry
        from elephas_tpu import obs

        return obs.default_registry()

    def _get_flight(self):
        if self._flight is not None:
            return self._flight
        from elephas_tpu import obs

        return obs.default_flight_recorder()

    # -- durable tee --------------------------------------------------------

    def attach_store(self, store) -> None:
        """Journal every subsequent fire/clear transition into ``store``
        (a ``TelemetryStore``) at transition time — alert history must
        survive SIGKILL, not just the next scrape. Idempotent."""
        with self._lock:
            if store not in self._stores:
                self._stores = self._stores + (store,)

    def detach_store(self, store) -> None:
        with self._lock:
            self._stores = tuple(s for s in self._stores if s is not store)

    # -- evaluation ---------------------------------------------------------

    @staticmethod
    def _match(metric: str, snap: Dict[str, float]) -> List[str]:
        if metric in snap:
            return [metric]
        prefix = metric + "{"
        return [k for k in snap if k.startswith(prefix)]

    def _measure(self, rule: AlertRule, key: str, value: float,
                 now: float) -> Optional[float]:
        """The number the predicate sees: the value itself, or the
        windowed per-second rate (None while under-sampled)."""
        if rule.mode == "value":
            return value
        ring = self._points.get((rule.name, key))
        if ring is None:
            # 512 slots at the 60 s default window tolerates ~8 Hz
            # evaluation before the oldest in-window point can rotate
            # out — far denser than any scrape loop in this repo.
            ring = self._points.setdefault((rule.name, key),
                                           HistoryRing(capacity=512))
        ring.push(now, value)
        return ring.rate(rule.window_s, now=now)

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One pass over every rule; returns alerts newly fired by THIS
        pass (the full ordered history stays in ``self.fired``)."""
        if now is None:
            now = self.clock()
        snap = self._get_registry().snapshot()
        new_fired: List[Dict[str, Any]] = []
        new_cleared: List[Dict[str, Any]] = []
        with self._lock:
            stores = self._stores
            for rule in self.rules:
                for key in self._match(rule.metric, snap):
                    measured = self._measure(rule, key, snap[key], now)
                    if measured is None:
                        continue
                    tripped = (measured > rule.threshold
                               if rule.predicate == ">"
                               else measured < rule.threshold)
                    state = (rule.name, key)
                    if not tripped:
                        if self._breached.get(state):
                            # latched breach evaluating clean: the
                            # clear transition is history worth keeping
                            # as much as the fire was.
                            new_cleared.append({
                                "rule": rule.name, "kind": rule.kind,
                                "severity": rule.severity, "metric": key,
                                "value": measured,
                                "threshold": rule.threshold, "t": now,
                            })
                        self._trips[state] = 0
                        self._breached[state] = False
                        continue
                    self._trips[state] = self._trips.get(state, 0) + 1
                    if (self._trips[state] >= rule.burn
                            and not self._breached.get(state)):
                        self._breached[state] = True
                        alert = {
                            "rule": rule.name, "kind": rule.kind,
                            "severity": rule.severity, "metric": key,
                            "value": measured,
                            "threshold": rule.threshold, "t": now,
                        }
                        self.fired.append(alert)
                        new_fired.append(alert)
        # Emit outside the engine lock: flight + registry take their own.
        for alert in new_fired:
            self._get_flight().note(
                alert["kind"], alert["severity"], rule=alert["rule"],
                metric=alert["metric"], value=alert["value"],
                threshold=alert["threshold"])
            self._get_registry().counter(
                "alerts_fired_total",
                help="SLO alert breaches fired, by rule",
                labelnames=("rule",)).labels(rule=alert["rule"]).inc()
        # Durable tee: both transition edges journal at transition time.
        for store in stores:
            try:
                for alert in new_fired:
                    store.record_alert("fire", alert)
                for alert in new_cleared:
                    store.record_alert("clear", alert)
            except Exception:
                pass
        return new_fired

    # -- read-out -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready state — the ``/alerts`` opsd route serves this."""
        with self._lock:
            active = [
                {"rule": name, "metric": key}
                for (name, key), hot in sorted(self._breached.items())
                if hot
            ]
            fired = list(self.fired)
        return {
            "rules": [r.to_dict() for r in self.rules],
            "active": active,
            "fired": fired,
            "fired_kinds": [a["kind"] for a in fired],
        }

    def scrape(self) -> Dict[str, Any]:
        """Evaluate, then snapshot — the one-call ops-route handler."""
        self.evaluate()
        return self.snapshot()
