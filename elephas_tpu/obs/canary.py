"""Blackbox canaries: synthetic probes through the real data paths.

Whitebox metrics can look healthy while users see nothing — a wedged
accept loop keeps exporting beautiful histograms. The canary answers
the only question that matters from the outside: *does a request
actually make it through, and how long does it take?*

- ``CanaryDriver`` — injects tagged low-cost probe requests (one-token
  prompt, two decode steps by default) through the engine's *real*
  submit path. The engine routes the finished probe back to the driver
  and — critically — never lets it reach the goodput ledger: real-
  traffic SLO accounting is identical with canaries on or off (pinned
  by test). Each probe is measured as an end-to-end blackbox SLI
  (submit-to-result wall time, TTFT) on the engine's injected clock,
  mirrored into ``serving_canary_probe_total`` /
  ``serving_canary_fail_total`` counters, and failures emit the
  ``canary_fail`` flight kind. The opsd ``/canary`` route serves
  ``snapshot()``.
- ``PSCanary`` — the parameter-server analogue: a zero-delta probe
  tree (built from the ``ShardPlan``'s dtype/shape rows, so it is
  plan-exact by construction and perturbs nothing) pushed and pulled
  through one wire sub-client *per shard*, yielding a write-read
  round-trip time for each shard independently — a dead primary shows
  up as that shard's probe failing while its peers stay green. When
  handed the in-process ``ShardGroup`` it also reads each standby's
  ``WalStreamer.lag()``, closing the PR-9 visibility gap.

Probe cost is a first-class concern: ``scripts/lm_bench.py --slo``
measures serving throughput with canaries on vs off (alternating
best-of-rounds, the tracing-overhead discipline) and
``scripts/bench_gate.py`` holds the overhead under 2%.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

DEFAULT_PROBE_PROMPT = (1,)
DEFAULT_PROBE_TOKENS = 2
DEFAULT_PROBE_TIMEOUT_S = 30.0
MAX_KEPT_RESULTS = 256


def _flight():
    from elephas_tpu import obs
    return obs.default_flight_recorder()


class _ProbeCounters:
    """Lazy default-registry counter pair, latched off on bind failure.

    Metric names arrive as full literals from the two call sites (the
    naming lint judges literals where they are written, and canary
    counters ride the ``serving_`` / ``ps_`` history-sampling prefixes).
    """

    def __init__(self, probe_name: str, fail_name: str):
        self._probe_name = probe_name
        self._fail_name = fail_name
        self._probe = None
        self._fail = None

    def bump(self, ok: bool) -> None:
        if self._probe is None:
            try:
                from elephas_tpu import obs
                reg = obs.default_registry()
                self._probe = reg.counter(
                    self._probe_name, help="blackbox canary probes attempted")
                self._fail = reg.counter(
                    self._fail_name, help="blackbox canary probes that failed")
            except Exception:
                self._probe = False
                self._fail = False
        if self._probe:
            self._probe.inc()
            if not ok:
                self._fail.inc()


class CanaryDriver:
    """End-to-end serving probe through the real submit path."""

    def __init__(self, engine, *, prompt: Sequence[int] = DEFAULT_PROBE_PROMPT,
                 max_new_tokens: int = DEFAULT_PROBE_TOKENS,
                 timeout_s: float = DEFAULT_PROBE_TIMEOUT_S,
                 clock: Optional[Callable[[], float]] = None):
        self.engine = engine
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.timeout_s = float(timeout_s)
        self.clock = clock if clock is not None else engine.clock
        self._lock = threading.Lock()
        self._results: List[Dict[str, object]] = []
        self.probes = 0
        self.failures = 0
        self._counters = _ProbeCounters(
            "serving_canary_probe_total", "serving_canary_fail_total")
        # The engine serves /canary from the attached driver.
        engine.attach_canary(self)

    def probe(self, timeout_s: Optional[float] = None) -> Dict[str, object]:
        """One blackbox round trip: submit → result, measured outside."""
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        t0 = self.clock()
        rec: Dict[str, object] = {
            "t": t0, "ok": False, "e2e_s": None, "ttft_s": None,
            "status": None, "error": None,
        }
        try:
            rid = self.engine.submit(
                self.prompt, max_new_tokens=self.max_new_tokens,
                timeout_s=timeout_s, canary=True,
            )
            res = self.engine.result(rid, timeout_s=timeout_s)
            rec["status"] = res.status
            rec["ttft_s"] = res.ttft_s
            rec["e2e_s"] = self.clock() - t0
            rec["ok"] = res.status == "completed"
        except Exception as exc:
            rec["error"] = f"{type(exc).__name__}: {exc}"
            rec["e2e_s"] = self.clock() - t0
        with self._lock:
            self.probes += 1
            if not rec["ok"]:
                self.failures += 1
            self._results.append(rec)
            del self._results[:-MAX_KEPT_RESULTS]
        self._counters.bump(bool(rec["ok"]))
        if not rec["ok"]:
            _flight().note(
                "canary_fail", "error", surface="serving",
                status=rec["status"], error=rec["error"],
            )
        return rec

    def snapshot(self) -> Dict[str, object]:
        """The opsd ``/canary`` document."""
        with self._lock:
            results = list(self._results)
            probes, failures = self.probes, self.failures
        e2e = [r["e2e_s"] for r in results if r["e2e_s"] is not None]
        return {
            "surface": "serving",
            "probes": probes,
            "failures": failures,
            "failure_ratio": (failures / probes) if probes else None,
            "e2e_s_avg": (sum(e2e) / len(e2e)) if e2e else None,
            "e2e_s_max": max(e2e) if e2e else None,
            "last": results[-1] if results else None,
        }


class PSCanary:
    """Per-shard write-read probe through ``ShardedParameterClient``."""

    def __init__(self, client, *, group=None,
                 clock: Callable[[], float] = time.monotonic):
        self.client = client
        self.plan = client.plan
        self.group = group
        self.clock = clock
        self._lock = threading.Lock()
        self.probes = 0
        self.failures = 0
        self._last: Optional[Dict[str, object]] = None
        self._counters = _ProbeCounters(
            "ps_canary_probe_total", "ps_canary_fail_total")
        # One zero-delta flat tree per shard, plan-exact by construction:
        # the server applies it additively, so state is unperturbed while
        # the full decode/apply/encode path still runs.
        self._zero: Dict[int, Dict[str, np.ndarray]] = {}
        for i, (path, row) in enumerate(zip(self.plan.paths, self.plan.rows)):
            dtype, shape = row[0], row[1]
            shard = self.plan.shard_of[i]
            self._zero.setdefault(shard, {})[path] = np.zeros(
                tuple(shape), dtype=np.dtype(dtype))

    def _probe_shard(self, shard: int) -> Dict[str, object]:
        rec: Dict[str, object] = {"shard": shard, "ok": False,
                                  "rtt_s": None, "error": None}
        t0 = self.clock()
        try:
            sub = self.client.shard_client(shard)
            sub.update_parameters(self._zero[shard])
            sub.get_parameters()
            rec["rtt_s"] = self.clock() - t0
            rec["ok"] = True
        except Exception as exc:
            rec["rtt_s"] = self.clock() - t0
            rec["error"] = f"{type(exc).__name__}: {exc}"
        return rec

    def probe(self) -> Dict[str, object]:
        """Write-read round trip against every shard, plus standby lag
        when the in-process group is visible."""
        t0 = self.clock()
        shards = [self._probe_shard(s) for s in range(self.plan.k)]
        ok = all(s["ok"] for s in shards)
        doc: Dict[str, object] = {
            "t": t0, "ok": ok, "shards": shards,
            "rtt_s_max": max((s["rtt_s"] for s in shards
                              if s["rtt_s"] is not None), default=None),
            "standby_lag": self._standby_lag(),
        }
        with self._lock:
            self.probes += 1
            if not ok:
                self.failures += 1
            self._last = doc
        self._counters.bump(ok)
        if not ok:
            failed = [s["shard"] for s in shards if not s["ok"]]
            _flight().note(
                "canary_fail", "error", surface="ps", shards=failed,
                error=next(s["error"] for s in shards if not s["ok"]),
            )
        return doc

    def _standby_lag(self) -> Optional[List[Dict[str, object]]]:
        if self.group is None:
            return None
        out = []
        for i in range(self.plan.k):
            streamer = self.group.streamer_of(i)
            if streamer is not None:
                try:
                    out.append({"shard": i, "lag": streamer.lag()})
                except Exception:
                    out.append({"shard": i, "lag": None})
        return out

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "surface": "ps",
                "probes": self.probes,
                "failures": self.failures,
                "failure_ratio": (self.failures / self.probes)
                if self.probes else None,
                "last": self._last,
            }
