"""Training-health accounting: staleness ledger + dynamics recording.

The async PS applies deltas that were computed against *old* parameter
versions — that staleness is the central trade the sync-frequency study
(SparkNet/DeepSpark, PAPERS.md) turns on, and bounded-staleness
admission (ROADMAP) can't land until it is measured. This module is the
measurement substrate:

- ``StalenessLedger`` — a rolling per-worker contribution table the PS
  feeds at ``apply_delta`` time: updates applied, cumulative/max version
  lag, last-seen version and time, bytes contributed. Served raw by the
  opsd ``/workers`` route, so "who is lagging, who is dominating" is one
  scrape away.
- ``record_staleness`` — the one-call apply-site hook: observes the lag
  into the labeled ``ps_staleness_versions`` histogram AND the ledger.
- ``tree_norm`` / ``record_unit_dynamics`` — per-unit training-dynamics
  telemetry for the engines (loss, delta norm, effective step size),
  recorded into registry gauges and tagged onto the live unit span so a
  merged trace answers "which worker's stale delta moved the loss".

Everything here is host-side numpy + dict bumps — no device syncs beyond
the host trees the engines already hold.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

import numpy as np

__all__ = [
    "StalenessLedger",
    "record_staleness",
    "record_unit_dynamics",
    "staleness_histogram",
    "tree_norm",
]

#: Version-lag bucket bounds: lags are small integers (how many applies
#: the server advanced past the worker's pull), not latencies.
STALENESS_BUCKETS = (0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128)


class StalenessLedger:
    """Rolling per-worker contribution table (thread-safe).

    ``record`` is a dict bump under one small lock — called by every PS
    push handler thread. ``samples`` keeps a bounded window of raw lags
    (all workers interleaved, arrival order) so read-out paths can
    report *exact* percentiles where the fixed-bucket histogram only
    interpolates; the window bounds memory, ``lag_sum``/``updates``
    stay exact forever.
    """

    def __init__(self, clock=time.monotonic, sample_capacity: int = 4096):
        self.clock = clock
        self._lock = threading.Lock()
        self._workers: Dict[str, Dict[str, Any]] = {}
        self._samples: deque = deque(maxlen=sample_capacity)
        self._unstamped = 0

    def record(self, worker: Optional[str], lag: Optional[int],
               nbytes: int = 0, version: Optional[int] = None,
               sync_interval: Optional[float] = None) -> None:
        """One applied delta. ``lag=None`` means the frame carried no
        ``seen_version`` stamp (legacy peer) — counted, not measured.
        ``sync_interval``: the pusher's self-reported adaptive
        units-per-push (last-write-wins; None when unstamped — the
        fleet SYNC column renders '-' for those workers)."""
        now = self.clock()
        with self._lock:
            if lag is None:
                self._unstamped += 1
                return
            row = self._row(worker)
            row["updates"] += 1
            row["lag_sum"] += int(lag)
            if lag > row["lag_max"]:
                row["lag_max"] = int(lag)
            row["bytes"] += int(nbytes)
            row["last_seen_version"] = version
            row["last_seen_s"] = now
            if sync_interval is not None:
                row["sync_interval"] = float(sync_interval)
            self._samples.append(int(lag))

    def _row(self, worker: Optional[str]) -> Dict[str, Any]:
        """Get-or-create a worker's row. Caller holds ``_lock``."""
        key = str(worker) if worker is not None else "unknown"
        row = self._workers.get(key)
        if row is None:
            row = self._workers[key] = {
                "updates": 0, "lag_sum": 0, "lag_max": 0,
                "bytes": 0, "last_seen_version": None,
                "last_seen_s": None, "rejected": 0, "damped": 0,
                "sync_interval": None,
            }
        return row

    def record_rejected(self, worker: Optional[str]) -> None:
        """One delta refused by the admission policy (hard bound).
        Rejected pushes do NOT count as updates — the ledger's
        ``updates`` column keeps meaning "deltas applied"."""
        with self._lock:
            self._row(worker)["rejected"] += 1

    def record_damped(self, worker: Optional[str]) -> None:
        """One delta applied at reduced weight (soft bound). The push
        still counts as an update (``record`` ran for it); this column
        just marks how many of them were decayed."""
        with self._lock:
            self._row(worker)["damped"] += 1

    def samples(self) -> list:
        """The retained lag window, arrival order (read-out paths build
        exact distributions from this; bounded by ``sample_capacity``)."""
        with self._lock:
            return list(self._samples)

    def lag_percentile(self, q: float) -> Optional[float]:
        """Exact quantile over the retained sample window; None if empty."""
        with self._lock:
            samples = sorted(self._samples)
        if not samples:
            return None
        rank = q * (len(samples) - 1)
        lo = int(math.floor(rank))
        hi = min(lo + 1, len(samples) - 1)
        return samples[lo] + (samples[hi] - samples[lo]) * (rank - lo)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready table — the ``/workers`` opsd route serves this."""
        with self._lock:
            workers = {
                k: dict(v, lag_mean=(v["lag_sum"] / v["updates"])
                        if v["updates"] else None)
                for k, v in self._workers.items()
            }
            samples = list(self._samples)
            unstamped = self._unstamped
        doc: Dict[str, Any] = {
            "workers": workers,
            "total_updates": sum(w["updates"] for w in workers.values()),
            "unstamped_updates": unstamped,
            "window_samples": len(samples),
        }
        for q, key in ((0.50, "lag_p50"), (0.95, "lag_p95"),
                       (0.99, "lag_p99")):
            doc[key] = self.lag_percentile(q)
        return doc


def staleness_histogram(registry):
    """The labeled per-worker staleness histogram (get-or-create)."""
    return registry.histogram(  # metric-ok: unit is version lag, not seconds
        "ps_staleness_versions",
        help="version lag of applied deltas (server version at apply "
             "minus the version the worker trained against)",
        buckets=STALENESS_BUCKETS, labelnames=("worker",),
    )


def record_staleness(ledger: Optional[StalenessLedger],
                     worker: Optional[str], lag: Optional[int],
                     nbytes: int = 0, version: Optional[int] = None,
                     registry=None,
                     sync_interval: Optional[float] = None) -> None:
    """The apply-site hook: ledger row + labeled histogram in one call.

    ``lag=None`` (unstamped legacy frame) still bumps the ledger's
    coverage counter but records no distribution point.
    """
    if ledger is not None:
        ledger.record(worker, lag, nbytes=nbytes, version=version,
                      sync_interval=sync_interval)
    if lag is not None and registry is not None:
        staleness_histogram(registry).labels(
            worker=str(worker) if worker is not None else "unknown"
        ).observe(lag)


def tree_norm(tree) -> float:
    """Global L2 norm over a host pytree's array leaves (numpy only —
    engines call this on trees they already hold on host)."""
    total = 0.0
    stack = [tree]
    while stack:
        node = stack.pop()
        if node is None:
            continue
        if isinstance(node, dict):
            stack.extend(node.values())
        elif isinstance(node, (list, tuple)):
            stack.extend(node)
        else:
            arr = np.asarray(node)
            if arr.dtype.kind in "fiu":
                flat = arr.astype(np.float64, copy=False).ravel()
                total += float(np.dot(flat, flat))
    return math.sqrt(total)


def record_unit_dynamics(registry, worker: Optional[str] = None, *,
                         loss: Optional[float] = None,
                         delta_norm: Optional[float] = None,
                         param_norm: Optional[float] = None,
                         span=None, **span_args) -> Dict[str, float]:
    """Record one training unit's dynamics; returns what was recorded.

    Effective step size is ``|delta| / |params|`` — the scale-free "how
    far did this update move the model" number the staleness trade study
    plots against lag. Gauges are last-write-wins per worker (the
    distribution lives in the trace; alert rules read the gauge).
    ``span`` (the live unit/push span, may be None when tracing is off)
    gets the same numbers as attributes.
    """
    key = str(worker) if worker is not None else "driver"
    out: Dict[str, float] = {}
    if loss is not None:
        out["unit_loss"] = float(loss)
        registry.gauge("train_unit_loss",
                       help="last per-unit training loss",
                       labelnames=("worker",)).labels(worker=key).set(loss)
    if delta_norm is not None:
        out["delta_norm"] = float(delta_norm)
        registry.gauge("train_delta_norm",
                       help="L2 norm of the last pushed/applied delta",
                       labelnames=("worker",)).labels(
                           worker=key).set(delta_norm)
        if param_norm is not None and param_norm > 0.0:
            step = float(delta_norm) / float(param_norm)
            out["effective_step"] = step
            registry.gauge(
                "train_effective_step",
                help="delta L2 norm over parameter L2 norm per unit",
                labelnames=("worker",)).labels(worker=key).set(step)
    if span is not None and out:
        span.note(**out, **span_args)
    return out
