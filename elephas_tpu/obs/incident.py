"""Post-mortem incident reconstruction from on-disk telemetry stores.

``obs/store.py`` leaves one journal directory per process slot;
``IncidentBuilder`` walks N of them *after the fact* — every process may
be SIGKILLed and gone — and rebuilds the incident as one causally
ordered timeline:

1. **Clock alignment.** Each record carries both clocks (``wall_s`` +
   ``mono_s``). Per (directory, boot id) the builder computes a wall
   base as the median of ``wall_s - mono_s`` — the same
   clockSync arithmetic ``trace_report.merge_dumps`` uses on trace
   dumps (``wall_at_export - mono_at_export + origin_mono``), but
   estimated per record and median-smoothed so a wall-clock step during
   the run cannot skew the whole boot. Aligned time is then
   ``base + mono_s``: monotonic within a boot, comparable across
   processes.

2. **Cross-boot stitching.** A warm restart reuses the slot's directory
   with a fresh boot id; boots are ordered by first aligned record and
   indexed, so "same slot, new boot" reads as one story (the restart's
   ``lifecycle: boot`` record is labeled a warm restart).

3. **Cross-store dedup + attribution.** In single-process test/bench
   topologies every co-hosted server tees the shared flight recorder
   into its own store, so one anomaly can appear in N journals. The
   builder collapses copies by event identity and attributes the event:
   to the process whose boot id the event's detail names, else to the
   process whose store directory the event's path detail points into,
   else to a synthetic ``driver`` process (group orchestrators note
   from no server's context). In real one-process-per-store
   deployments this is a no-op.

4. **Triggering event + digest.** The trigger is the earliest
   ``error``-severity timeline entry (first ``warn`` as fallback). The
   incident digest is replay-stable and order-canonical: sha256 over
   the *sorted set* of stable identities (record kind, attributed role,
   event kind/rule/transition, severity) — timestamps, boot ids, pids,
   ports and repetition counts are all excluded, so two seeded runs of
   the same chaos arc produce the same digest while any new anomaly
   kind changes it.

Metric ticks and span summaries are counted and excerpted (ticks within
a window of the trigger join the timeline for context) but never enter
the digest — their values are timing-dependent by nature.
"""

from __future__ import annotations

import hashlib
import json
import os
import statistics
from typing import Any, Dict, List, Optional, Tuple

from elephas_tpu.obs import store as _store

__all__ = ["IncidentBuilder", "render_markdown"]

#: Detail keys that name the *origin boot* of a teed flight event.
_BOOT_KEYS = ("boot", "old_boot", "dead_boot")
#: Detail keys that name an on-disk path near the origin's store dir.
_PATH_KEYS = ("wal_dir", "path", "dir", "out_dir")

_TIMELINE_KINDS = ("flight", "alert", "lifecycle")


def _event_name(rec: Dict[str, Any]) -> str:
    """The human name of a timeline record: flight kind, alert
    rule:transition, or lifecycle event."""
    data = rec.get("data") or {}
    k = rec.get("k")
    if k == "flight":
        return str(data.get("kind", "?"))
    if k == "alert":
        return f"{data.get('rule', '?')}:{data.get('transition', '?')}"
    if k == "lifecycle":
        return str(data.get("event", "?"))
    return str(k)


class IncidentBuilder:
    """Walks N store directories and rebuilds one incident. Purely
    read-only over the directories — safe with every owner dead."""

    def __init__(self):
        self._stores: List[Tuple[str, str]] = []  # (name, dir)

    def add_store(self, directory: str, name: Optional[str] = None) -> str:
        """Register one store directory; returns the process name used
        (defaults to the directory path minus the ``/telemetry`` leaf)."""
        if name is None:
            d = os.path.normpath(directory)
            base = os.path.basename(d)
            name = (os.path.basename(os.path.dirname(d))
                    if base == "telemetry" else base) or d
        self._stores.append((name, directory))
        return name

    def discover(self, root: str) -> List[str]:
        """Register every store directory under ``root`` (named by their
        relative path); returns the names added."""
        names = []
        for d in _store.store_dirs(root):
            rel = os.path.relpath(d, root)
            if os.path.basename(rel) == "telemetry":
                rel = os.path.dirname(rel) or rel
            rel = rel.replace(os.sep, "/")
            names.append(self.add_store(d, name=rel if rel != "." else None))
        return names

    # -- the build ---------------------------------------------------------

    def build(self, metric_window_s: float = 2.0) -> Dict[str, Any]:
        procs: List[Dict[str, Any]] = []
        all_entries: List[Dict[str, Any]] = []
        metric_entries: List[Dict[str, Any]] = []
        counts: Dict[str, int] = {}
        boots_by_proc: Dict[str, List[str]] = {}

        for name, directory in self._stores:
            dump = _store.read_store(directory)
            by_boot: Dict[str, List[Dict[str, Any]]] = {}
            for rec in dump["records"]:
                counts[rec.get("k", "?")] = counts.get(rec.get("k", "?"),
                                                       0) + 1
                by_boot.setdefault(str(rec.get("boot", "?")),
                                   []).append(rec)
            # clockSync per boot: median wall base, aligned = base + mono.
            boot_meta = []
            for boot, recs in by_boot.items():
                base = statistics.median(
                    float(r.get("wall_s", 0.0)) - float(r.get("mono_s", 0.0))
                    for r in recs
                )
                for r in recs:
                    r["_t"] = base + float(r.get("mono_s", 0.0))
                recs.sort(key=lambda r: (r["_t"], r.get("seq", 0)))
                boot_meta.append({
                    "boot": boot,
                    "role": recs[-1].get("role", ""),
                    "records": len(recs),
                    "first_t": recs[0]["_t"],
                    "last_t": recs[-1]["_t"],
                })
            boot_meta.sort(key=lambda b: b["first_t"])
            for i, b in enumerate(boot_meta):
                b["boot_index"] = i
            index_of = {b["boot"]: b["boot_index"] for b in boot_meta}
            boots_by_proc[name] = [b["boot"] for b in boot_meta]

            for boot, recs in by_boot.items():
                for r in recs:
                    entry = {
                        "t": r["_t"],
                        "wall_s": r.get("wall_s"),
                        "mono_s": r.get("mono_s"),
                        "proc": name,
                        "role": r.get("role", ""),
                        "boot": boot,
                        "boot_index": index_of[boot],
                        "seq": r.get("seq", 0),
                        "k": r.get("k"),
                        "name": _event_name(r),
                        "severity": r.get("severity"),
                        "data": r.get("data") or {},
                    }
                    if entry["k"] in _TIMELINE_KINDS:
                        all_entries.append(entry)
                    elif entry["k"] == "metric":
                        metric_entries.append(entry)
            procs.append({
                "name": name,
                "dir": dump["dir"],
                "roles": sorted({b["role"] for b in boot_meta}),
                "boots": boot_meta,
                "records": len(dump["records"]),
                "bytes": dump["bytes"],
                "segments": dump["segments"],
                "corrupt_tails": len(dump["corrupt_tails"]),
            })

        deduped = self._dedupe_flight(all_entries, procs)
        timeline = [e for e in all_entries if not e.pop("_drop", False)]
        for e in timeline:
            if (e["k"] == "lifecycle" and e["name"] == "boot"
                    and e["boot_index"] > 0):
                e["name"] = "boot (warm restart)"
        timeline.sort(key=lambda e: (e["t"], e["proc"], e["boot_index"],
                                     e["seq"]))

        trigger = self._find_trigger(timeline)
        if trigger is not None and metric_entries:
            near = [e for e in metric_entries
                    if abs(e["t"] - trigger["t"]) <= metric_window_s]
            near.sort(key=lambda e: (e["t"], e["proc"], e["seq"]))
            timeline.extend(near[:20])
            timeline.sort(key=lambda e: (e["t"], e["proc"], e["boot_index"],
                                         e["seq"]))

        digest = self._digest(timeline)
        return {
            "stores": len(self._stores),
            "processes": procs,
            "counts": counts,
            "deduped_flight": deduped,
            "timeline": timeline,
            "triggering_event": trigger,
            "digest": digest,
            "boots_by_proc": boots_by_proc,
        }

    # -- internals ---------------------------------------------------------

    def _dedupe_flight(self, entries: List[Dict[str, Any]],
                       procs: List[Dict[str, Any]]) -> int:
        """Collapse cross-store copies of the same flight event; keep
        exactly one attributed copy per event (see module docstring)."""
        boots_of = {p["name"]: {b["boot"] for b in p["boots"]}
                    for p in procs}
        dir_of = {p["name"]: os.path.normpath(p["dir"]) for p in procs}
        groups: Dict[Tuple, List[Dict[str, Any]]] = {}
        for e in entries:
            if e["k"] != "flight":
                continue
            d = e["data"]
            key = (
                d.get("kind"),
                d.get("trace_id"),
                json.dumps(d.get("detail", {}), sort_keys=True),
                round(float(e.get("wall_s") or 0.0), 6),
                round(float(e.get("mono_s") or 0.0), 6),
            )
            groups.setdefault(key, []).append(e)
        deduped = 0
        for copies in groups.values():
            if len(copies) <= 1:
                continue
            keep = self._attribute(copies, boots_of, dir_of)
            for e in copies:
                if e is not keep:
                    e["_drop"] = True
                    deduped += 1
        return deduped

    @staticmethod
    def _attribute(copies: List[Dict[str, Any]],
                   boots_of: Dict[str, set],
                   dir_of: Dict[str, str]) -> Dict[str, Any]:
        detail = (copies[0]["data"] or {}).get("detail") or {}
        for key in _BOOT_KEYS:
            boot = detail.get(key)
            if not boot:
                continue
            for e in copies:
                if boot in boots_of.get(e["proc"], ()):
                    return e
        for key in _PATH_KEYS:
            path = detail.get(key)
            if not isinstance(path, str) or not path:
                continue
            norm = os.path.normpath(path)
            for e in copies:
                d = dir_of.get(e["proc"], "")
                # The store dir is <slot_dir>/telemetry; a detail path
                # anywhere under the slot dir claims the event.
                slot = os.path.dirname(d) or d
                if d and (norm == slot or norm.startswith(slot + os.sep)
                          or d.startswith(norm + os.sep) or d == norm):
                    return e
        # Orchestrator-noted event with no owning process: keep one
        # deterministic copy, re-attributed to the synthetic driver
        # slot so replays agree regardless of which stores saw it.
        keep = min(copies, key=lambda e: (e["proc"], e["boot_index"],
                                          e["seq"]))
        keep["proc"] = "(shared)"
        keep["role"] = "driver"
        return keep

    @staticmethod
    def _find_trigger(timeline: List[Dict[str, Any]]) -> Optional[Dict]:
        for floor in ("error", "warn"):
            for e in timeline:
                if e.get("severity") == floor:
                    return {
                        "kind": e["name"],
                        "k": e["k"],
                        "severity": e["severity"],
                        "proc": e["proc"],
                        "role": e["role"],
                        "t": e["t"],
                        "detail": (e["data"] or {}).get("detail",
                                                        e["data"]),
                    }
        return None

    @staticmethod
    def _digest(timeline: List[Dict[str, Any]]) -> str:
        """Replay-stable, order-canonical: sorted SET of stable
        identities — no timestamps, boots, pids, ports, counts."""
        idents = set()
        for e in timeline:
            if e["k"] not in _TIMELINE_KINDS:
                continue
            idents.add("|".join((
                str(e["k"]), str(e["role"]), str(e["name"]),
                str(e.get("severity")),
            )))
        blob = "\n".join(sorted(idents)).encode("utf-8")
        return hashlib.sha256(blob).hexdigest()[:16]


def render_markdown(incident: Dict[str, Any],
                    title: str = "Incident report") -> str:
    """One self-contained markdown post-mortem: header facts, the
    triggering event by name, per-process inventory, and the causally
    ordered timeline with times relative to the first entry."""
    lines = [f"# {title}", ""]
    trig = incident.get("triggering_event")
    timeline = incident.get("timeline", [])
    t0 = timeline[0]["t"] if timeline else 0.0
    lines.append(f"- stores: {incident.get('stores', 0)} process "
                 f"director{'y' if incident.get('stores') == 1 else 'ies'}")
    counts = incident.get("counts", {})
    lines.append("- records: " + ", ".join(
        f"{k}={counts[k]}" for k in sorted(counts)) if counts else
        "- records: none")
    if incident.get("deduped_flight"):
        lines.append(f"- cross-store flight copies collapsed: "
                     f"{incident['deduped_flight']}")
    if trig is not None:
        lines.append(
            f"- **triggering event**: `{trig['kind']}` "
            f"({trig['severity']}) on `{trig['role'] or trig['proc']}` "
            f"at t+{trig['t'] - t0:.3f}s"
        )
    else:
        lines.append("- **triggering event**: none found "
                     "(no warn/error records)")
    lines.append(f"- incident digest: `{incident.get('digest', '')}`")
    lines.append("")
    lines.append("## Processes")
    lines.append("")
    lines.append("| proc | role(s) | boots | records | bytes "
                 "| corrupt tails |")
    lines.append("|---|---|---|---|---|---|")
    for p in incident.get("processes", []):
        lines.append(
            f"| {p['name']} | {', '.join(p['roles'])} | "
            f"{len(p['boots'])} | {p['records']} | {p['bytes']} | "
            f"{p['corrupt_tails']} |"
        )
    lines.append("")
    lines.append("## Timeline")
    lines.append("")
    lines.append("| t (s) | proc | role | kind | severity | event |")
    lines.append("|---|---|---|---|---|---|")
    for e in timeline:
        if e["k"] == "metric":
            values = (e["data"] or {}).get("values", {})
            keys = sorted(values)[:3]
            event = "tick " + " ".join(
                f"{k}={values[k]:.4g}" for k in keys)
        else:
            event = e["name"]
            if e["boot_index"] > 0 and e["k"] != "lifecycle":
                event += f" (boot#{e['boot_index']})"
        marker = " **←trigger**" if (
            trig is not None and e["t"] == trig["t"]
            and e["name"] == trig["kind"] and e["proc"] == trig["proc"]
        ) else ""
        lines.append(
            f"| +{e['t'] - t0:.3f} | {e['proc']} | {e['role']} | "
            f"{e['k']} | {e.get('severity') or '-'} | {event}{marker} |"
        )
    lines.append("")
    return "\n".join(lines)
