"""Unified observability layer: span tracing + metrics registry.

Everything here is HOST-side only and allocation-light — no device
syncs, no per-sample storage — so instrumentation can stay on inside
the pipelined serving scheduler's overlap window (the bench guardrail
in ``scripts/lm_bench.py`` pins the traced/untraced gap under 2%).

Two process-global defaults back cross-cutting instrumentation (the
training engines, parameter-server clients, and compile counters all
record through them):

- ``default_tracer()`` — starts as the shared disabled ``NULL_TRACER``
  (every span is a no-op); ``enable_tracing()`` swaps in a live ring.
- ``default_registry()`` — always live (counters/gauges/histograms are
  a few ints each); scrape with ``default_registry().expose_text()``.
- ``default_flight_recorder()`` — bounded anomaly ring (retrace storms,
  heartbeat flaps, rejections, WAL restores); live by default since
  anomalies are rare by construction, swappable for tests via
  ``set_default_flight_recorder()``.

The serving ``InferenceEngine`` instead takes an explicit ``tracer=``
(its clock is injectable and the tracer must share it); it falls back
to the global default when none is passed.

Distributed trace context rides along from ``obs.trace``:
``new_context()``/``activate()``/``current_context()`` are re-exported
here so call sites can root and adopt traces without a second import.
"""

from __future__ import annotations

import time
from typing import Optional

from elephas_tpu.obs.registry import (  # noqa: F401
    Counter,
    Family,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_latency_buckets,
)
from elephas_tpu.obs.trace import (  # noqa: F401
    NULL_TRACER,
    SpanEvent,
    TraceContext,
    Tracer,
    activate,
    current_context,
    new_context,
)
from elephas_tpu.obs.flight import (  # noqa: F401
    KINDS,
    NULL_FLIGHT_RECORDER,
    FlightEvent,
    FlightRecorder,
)
from elephas_tpu.obs.health import (  # noqa: F401
    StalenessLedger,
    record_staleness,
    record_unit_dynamics,
    tree_norm,
)
from elephas_tpu.obs.alerts import (  # noqa: F401
    RULE_NAMES,
    AlertEngine,
    AlertRule,
    default_rules,
)
from elephas_tpu.obs.history import (  # noqa: F401
    DEFAULT_SAMPLE_PREFIXES,
    HistoryRing,
    HistorySampler,
)
from elephas_tpu.obs.devprof import (  # noqa: F401
    DeviceProfiler,
    device_memory_snapshot,
    record_device_memory,
)
from elephas_tpu.obs.fleet import (  # noqa: F401
    FleetAggregator,
    ProcessRegistry,
    parse_prometheus_text,
)
from elephas_tpu.obs.load import (  # noqa: F401
    LoadScore,
    LoadSnapshot,
    LoadTracker,
    instant_load,
)
from elephas_tpu.obs.slo import (  # noqa: F401
    GoodputLedger,
    SLOObjective,
    default_objectives,
)
from elephas_tpu.obs.canary import (  # noqa: F401
    CanaryDriver,
    PSCanary,
)
from elephas_tpu.obs.tenancy import (  # noqa: F401
    DEFAULT_TENANT,
    CostLedger,
    merge_tenant_docs,
    tenant_rules,
)
from elephas_tpu.obs.store import (  # noqa: F401
    RECORD_KINDS,
    TelemetryStore,
    iter_records,
    read_store,
    store_dirs,
)
from elephas_tpu.obs.incident import (  # noqa: F401
    IncidentBuilder,
    render_markdown,
)

_tracer: Tracer = NULL_TRACER
_registry = MetricsRegistry()
_flight = FlightRecorder()


def default_tracer() -> Tracer:
    """The process-global tracer (disabled until ``enable_tracing``)."""
    return _tracer


def set_default_tracer(tracer: Optional[Tracer]) -> Tracer:
    """Install ``tracer`` as the global default (None → disabled)."""
    global _tracer
    _tracer = tracer if tracer is not None else NULL_TRACER
    return _tracer


def enable_tracing(capacity: int = 65536, clock=time.monotonic,
                   annotate_device: bool = True) -> Tracer:
    """Swap a live ring in as the global tracer and return it."""
    return set_default_tracer(
        Tracer(capacity=capacity, clock=clock,
               annotate_device=annotate_device)
    )


def disable_tracing() -> None:
    """Back to the shared no-op tracer (recorded events are dropped)."""
    set_default_tracer(None)


def default_registry() -> MetricsRegistry:
    """The process-global metrics registry (always live)."""
    return _registry


def default_flight_recorder() -> FlightRecorder:
    """The process-global anomaly ring (live by default)."""
    return _flight


def set_default_flight_recorder(
        recorder: Optional[FlightRecorder]) -> FlightRecorder:
    """Install ``recorder`` as the global default (None → disabled)."""
    global _flight
    _flight = recorder if recorder is not None else NULL_FLIGHT_RECORDER
    return _flight
