"""Time-series history rings: counters/gauges become rates and windows.

Every scrape surface built so far (``/metrics``, ``/workers``,
``/alerts``) is a *point-in-time* snapshot — it cannot answer "what
happened in the last 60 s before the alert fired", and every consumer
that needed a rate (the alert engine's ``mode="rate"`` rules) grew its
own ad-hoc two-point bookkeeping. This module is the one substrate for
both:

- ``HistoryRing`` — a fixed-capacity ``(t, value)`` ring with
  preallocated storage (pushing in steady state writes two floats into
  existing slots — no allocation, no GC pressure on the sampling path)
  exposing windowed reads: per-second rate over the trailing window,
  min/max/last, sample count.
- ``HistorySampler`` — samples *selected* registry snapshot keys
  (prefix-matched: all of ``ps_*``, ``serving_*``, ... by default) into
  one ring per key at a configurable period, either explicitly
  (``tick()`` — tests, bench checkpoints) or on a background daemon
  thread (``start()`` — what a mounted ops endpoint runs). The opsd
  ``/history?window=`` route serves ``snapshot(window_s)``.

The alert engine's windowed-rate rules evaluate on these rings (one
private ring per (rule, matched key)), replacing their original
two-point deque deltas — same semantics (oldest retained point inside
the window to the newest), one implementation.

Rate semantics: ``rate(window_s, now)`` considers samples with
``now - t <= window_s``, needs at least two, and differentiates the
oldest retained against the newest — so a counter sampled every second
over a 60 s window yields the true trailing-minute per-second rate, and
an under-sampled ring answers ``None`` instead of a made-up number.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["HistoryRing", "HistorySampler", "DEFAULT_SAMPLE_PREFIXES"]

#: Registry snapshot keys the sampler tracks when no explicit selection
#: is given: the cross-process data-path counters, serving latencies,
#: training dynamics, alert firings, and device memory watermarks.
#: Histogram percentile expansions (`*_p50`...) ride along under their
#: family prefix — a percentile's history is exactly what "p95 over the
#: last minute" needs.
DEFAULT_SAMPLE_PREFIXES = (
    "ps_",
    "serving_",
    "train_",
    "alerts_",
    "device_mem_",
    "tracer_",
    "retrace_",
)


class HistoryRing:
    """Fixed-capacity time-series ring (thread-safe).

    Storage is two preallocated float lists indexed modulo capacity:
    ``push`` in steady state is two list writes + integer bumps under a
    small lock — zero allocation, so a 1 Hz sampler tracking hundreds of
    keys costs microseconds, forever. Reads build small lists (readout
    is rare and not on the sampling path).
    """

    __slots__ = ("capacity", "_t", "_v", "_n", "_next", "_lock")

    def __init__(self, capacity: int = 512):
        if capacity < 2:
            raise ValueError(
                f"capacity must be >= 2 (a rate needs two points), "
                f"got {capacity}")
        self.capacity = capacity
        self._t = [0.0] * capacity
        self._v = [0.0] * capacity
        self._n = 0  # samples retained (<= capacity)
        self._next = 0  # slot the next push writes
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return self._n

    def push(self, t: float, value: float) -> None:
        with self._lock:
            self._t[self._next] = float(t)
            self._v[self._next] = float(value)
            self._next = (self._next + 1) % self.capacity
            if self._n < self.capacity:
                self._n += 1

    def samples(self, window_s: Optional[float] = None,
                now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Retained ``(t, value)`` pairs oldest-first; with ``window_s``,
        only those with ``now - t <= window_s`` (``now`` defaults to the
        newest retained timestamp)."""
        with self._lock:
            n, nxt = self._n, self._next
            out = [(self._t[(nxt - n + i) % self.capacity],
                    self._v[(nxt - n + i) % self.capacity])
                   for i in range(n)]
        if window_s is None or not out:
            return out
        if now is None:
            now = out[-1][0]
        return [(t, v) for t, v in out if now - t <= window_s]

    def last(self) -> Optional[Tuple[float, float]]:
        with self._lock:
            if self._n == 0:
                return None
            i = (self._next - 1) % self.capacity
            return (self._t[i], self._v[i])

    def rate(self, window_s: float, now: Optional[float] = None
             ) -> Optional[float]:
        """Per-second rate of change over the trailing window: newest
        retained sample vs the oldest one still inside it. ``None``
        until two samples land in the window (never a made-up number)."""
        pts = self.samples(window_s=window_s, now=now)
        if len(pts) < 2:
            return None
        t0, v0 = pts[0]
        t1, v1 = pts[-1]
        if t1 <= t0:
            return None
        return (v1 - v0) / (t1 - t0)

    def stats(self, window_s: Optional[float] = None,
              now: Optional[float] = None) -> Dict[str, Optional[float]]:
        """JSON-ready windowed roll-up: n / last / min / max / rate."""
        pts = self.samples(window_s=window_s, now=now)
        if not pts:
            return {"n": 0, "last": None, "min": None, "max": None,
                    "rate_per_s": None, "span_s": None}
        values = [v for _, v in pts]
        t0, t1 = pts[0][0], pts[-1][0]
        rate = None
        if len(pts) >= 2 and t1 > t0:
            rate = (pts[-1][1] - pts[0][1]) / (t1 - t0)
        return {
            "n": len(pts),
            "last": pts[-1][1],
            "min": min(values),
            "max": max(values),
            "rate_per_s": rate,
            "span_s": t1 - t0,
        }


class HistorySampler:
    """Samples selected registry snapshot keys into per-key rings.

    ``select`` is a tuple of key prefixes (exact keys match their own
    prefix); the default tracks the package's cross-process families
    (``DEFAULT_SAMPLE_PREFIXES``). A ring is allocated the first time a
    key appears — after that, steady state allocates nothing.

    Driving: ``tick(now)`` samples once (tests and bench checkpoints
    call it on an injected clock); ``maybe_tick(now)`` respects
    ``period_s``; ``start()`` runs ``tick`` on a background daemon
    thread every ``period_s`` wall seconds (what a mounted ops endpoint
    uses — sampling must not depend on being scraped). ``extra_fn``
    (e.g. ``devprof.record_device_memory``) runs before each sample so
    pull-style gauges are fresh in the snapshot the tick reads.
    """

    def __init__(self, registry=None,
                 select: Iterable[str] = DEFAULT_SAMPLE_PREFIXES,
                 period_s: float = 1.0, capacity: int = 512,
                 clock=time.monotonic, extra_fn=None):
        if period_s <= 0:
            raise ValueError(f"period_s must be > 0, got {period_s}")
        self._registry = registry
        self.select = tuple(select)
        self.period_s = float(period_s)
        self.capacity = int(capacity)
        self.clock = clock
        self.extra_fn = extra_fn
        self.rings: Dict[str, HistoryRing] = {}
        self.ticks = 0
        self._last_tick: Optional[float] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stores: tuple = ()  # durable tees (obs/store.py), COW

    def _get_registry(self):
        if self._registry is not None:
            return self._registry
        from elephas_tpu import obs

        return obs.default_registry()

    def _selected(self, key: str) -> bool:
        return any(key.startswith(p) for p in self.select)

    # -- sampling -----------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> int:
        """Sample every selected snapshot key once; returns how many
        keys were recorded. Safe to call from any thread."""
        if now is None:
            now = self.clock()
        if self.extra_fn is not None:
            try:
                self.extra_fn()
            except Exception:
                pass  # a broken watermark probe must not stop sampling
        snap = self._get_registry().snapshot()
        recorded = 0
        sampled: Dict[str, float] = {}
        for key, value in snap.items():
            if not self._selected(key):
                continue
            if not isinstance(value, (int, float)) or isinstance(value, bool):
                continue
            if math.isnan(value):
                continue
            ring = self.rings.get(key)
            if ring is None:
                with self._lock:
                    ring = self.rings.setdefault(
                        key, HistoryRing(capacity=self.capacity))
            ring.push(now, value)
            sampled[key] = float(value)
            recorded += 1
        self.ticks += 1
        self._last_tick = now
        # Durable tee: the tick's sampled name→value map journals as one
        # ``metric`` record, so a post-mortem has the metric excerpts
        # the in-memory rings would have lost with the process.
        for store in self._stores:
            try:
                store.record_metrics(sampled, self.ticks)
            except Exception:
                pass
        return recorded

    # -- durable tee --------------------------------------------------------

    def attach_store(self, store) -> None:
        """Journal every subsequent tick into ``store``. Idempotent."""
        with self._lock:
            if store not in self._stores:
                self._stores = self._stores + (store,)

    def detach_store(self, store) -> None:
        with self._lock:
            self._stores = tuple(s for s in self._stores if s is not store)

    def maybe_tick(self, now: Optional[float] = None) -> bool:
        """``tick`` iff at least ``period_s`` elapsed since the last."""
        if now is None:
            now = self.clock()
        if self._last_tick is not None and now - self._last_tick < self.period_s:
            return False
        self.tick(now)
        return True

    # -- background driving -------------------------------------------------

    def start(self) -> "HistorySampler":
        """Run ``tick`` every ``period_s`` on a daemon thread
        (idempotent). The thread waits on an Event, so ``stop()``
        returns promptly."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.period_s):
                self.tick()

        self._thread = threading.Thread(
            target=loop, name="obs-history-sampler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None

    # -- read-out -----------------------------------------------------------

    def snapshot(self, window_s: Optional[float] = None,
                 now: Optional[float] = None) -> Dict[str, object]:
        """JSON-ready dump — the ``/history?window=`` route serves this:
        one windowed stats row per tracked key, plus sampler config."""
        if now is None and window_s is not None:
            now = self.clock()
        with self._lock:
            keys = sorted(self.rings)
        return {
            "period_s": self.period_s,
            "capacity": self.capacity,
            "window_s": window_s,
            "ticks": self.ticks,
            "series": {
                k: self.rings[k].stats(window_s=window_s, now=now)
                for k in keys
            },
        }
