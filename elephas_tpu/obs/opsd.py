"""opsd: threaded HTTP introspection endpoint for live processes.

Every long-lived process in the system — PS servers, the serving
``InferenceEngine`` frontend — can mount one of these and answer, while
under load, the questions that today require attaching a debugger:

- ``GET /metrics`` — Prometheus text exposition of the process registry
  (scrapeable by a stock Prometheus server);
- ``GET /healthz`` — liveness + an optional health summary (PS servers
  wire their ``MembershipView``/failure-detector state in);
- ``GET /trace``   — the span ring as Chrome-trace JSON *with the
  clockSync block*, which is exactly the per-process dump
  ``scripts/trace_report.py --merge`` aligns across machines;
- ``GET /vars``    — process identity and config (boot id, buffer
  version, bind address) for "which incarnation am I talking to";
- ``GET /flight``  — the anomaly flight-recorder ring;
- ``GET /workers`` — the PS's per-worker staleness/contribution ledger
  (``obs.health.StalenessLedger.snapshot``);
- ``GET /alerts``  — the SLO alert engine's rules, active breaches, and
  ordered fired history (each scrape runs one evaluation pass).

Security: opsd binds **loopback by default** (``127.0.0.1``). It serves
unauthenticated process internals — trace args can contain request ids
and config values — so exposing it beyond the host is an explicit
decision: pass ``host=`` or set ``ELEPHAS_OPS_BIND``. This mirrors the
PS servers' own ``ELEPHAS_PS_BIND`` convention.

The server is a ``ThreadingHTTPServer`` on a daemon thread: requests
never touch the training/serving hot paths beyond the GIL, handlers
only *read* shared structures (registry exposition and ring snapshots
are already lock-guarded copies), and ``stop()`` is idempotent.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

__all__ = ["OpsServer"]


def _default_bind_host() -> str:
    # Loopback unless the operator explicitly opts into exposure.
    return os.environ.get("ELEPHAS_OPS_BIND", "127.0.0.1")


class OpsServer:
    """Mountable introspection endpoint (see module docstring).

    Parameters
    ----------
    port: TCP port; 0 picks a free one (read ``.port`` after
        ``start()``).
    host: bind address; defaults to loopback / ``ELEPHAS_OPS_BIND``.
    registry / tracer / flight: the surfaces to serve; default to the
        process-global ones resolved lazily at request time (so a
        later ``enable_tracing()`` is picked up without a remount).
    vars_fn: extra ``/vars`` content, e.g. the PS server's boot id and
        buffer version — called per request so values are live.
    health_fn: extra ``/healthz`` content (membership summary). If it
        raises, ``/healthz`` answers 500 — a health route that lies is
        worse than one that fails.
    workers_fn: the ``/workers`` payload (a staleness-ledger snapshot);
        the route answers an empty table when unset, so scrapers can
        probe any process uniformly.
    alerts_fn: the ``/alerts`` payload (an alert-engine scrape); answers
        an empty rule pack when unset.
    """

    def __init__(self, port: int = 0, host: Optional[str] = None,
                 registry=None, tracer=None, flight=None,
                 vars_fn: Optional[Callable[[], Dict]] = None,
                 health_fn: Optional[Callable[[], Dict]] = None,
                 workers_fn: Optional[Callable[[], Dict]] = None,
                 alerts_fn: Optional[Callable[[], Dict]] = None):
        self._requested_port = port
        self.host = host if host is not None else _default_bind_host()
        self._registry = registry
        self._tracer = tracer
        self._flight = flight
        self._vars_fn = vars_fn
        self._health_fn = health_fn
        self._workers_fn = workers_fn
        self._alerts_fn = alerts_fn
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started_wall = None
        self.port: Optional[int] = None

    # Lazy resolution: a tracer enabled after mount is still served.
    def _get_registry(self):
        if self._registry is not None:
            return self._registry
        from elephas_tpu import obs
        return obs.default_registry()

    def _get_tracer(self):
        if self._tracer is not None:
            return self._tracer
        from elephas_tpu import obs
        return obs.default_tracer()

    def _get_flight(self):
        if self._flight is not None:
            return self._flight
        from elephas_tpu import obs
        return obs.default_flight_recorder()

    def start(self) -> "OpsServer":
        if self._httpd is not None:
            return self
        ops = self
        self._started_wall = time.time()

        class Handler(BaseHTTPRequestHandler):
            # opsd must never spam the process stdout per scrape.
            def log_message(self, *a):  # noqa: D102
                pass

            def _send(self, code: int, body: bytes,
                      content_type: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, doc) -> None:
                self._send(code, json.dumps(doc).encode())

            def do_GET(self):  # noqa: N802
                try:
                    if self.path == "/metrics":
                        text = ops._get_registry().expose_text()
                        self._send(200, text.encode(),
                                   "text/plain; version=0.0.4")
                    elif self.path == "/healthz":
                        doc = {"status": "ok",
                               "uptime_s": time.time() - ops._started_wall}
                        if ops._health_fn is not None:
                            doc.update(ops._health_fn())
                        self._send_json(200, doc)
                    elif self.path == "/trace":
                        self._send_json(200,
                                        ops._get_tracer().export_chrome())
                    elif self.path == "/vars":
                        doc = {"pid": os.getpid(),
                               "ops_host": ops.host,
                               "ops_port": ops.port}
                        if ops._vars_fn is not None:
                            doc.update(ops._vars_fn())
                        self._send_json(200, doc)
                    elif self.path == "/flight":
                        self._send_json(200, ops._get_flight().snapshot())
                    elif self.path == "/workers":
                        doc = (ops._workers_fn() if ops._workers_fn
                               is not None else
                               {"workers": {}, "total_updates": 0,
                                "unstamped_updates": 0})
                        self._send_json(200, doc)
                    elif self.path == "/alerts":
                        doc = (ops._alerts_fn() if ops._alerts_fn
                               is not None else
                               {"rules": [], "active": [], "fired": [],
                                "fired_kinds": []})
                        self._send_json(200, doc)
                    else:
                        self._send_json(404, {"error": "not found",
                                              "path": self.path})
                except Exception as exc:  # surface, don't hang the scrape
                    try:
                        self._send_json(500, {"error": repr(exc)})
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"opsd:{self.port}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
