"""opsd: threaded HTTP introspection endpoint for live processes.

Every long-lived process in the system — PS servers, the serving
``InferenceEngine`` frontend, trainers — can mount one of these and
answer, while under load, the questions that today require attaching a
debugger:

- ``GET /metrics`` — Prometheus text exposition of the process registry
  (scrapeable by a stock Prometheus server), stamped with an
  ``elephas_process_info{role=,boot=,pid=}`` identity line so merged
  dumps stay attributable without out-of-band context;
- ``GET /healthz`` — liveness + an optional health summary (PS servers
  wire their ``MembershipView``/failure-detector state in);
- ``GET /trace``   — the span ring as Chrome-trace JSON *with the
  clockSync block*, which is exactly the per-process dump
  ``scripts/trace_report.py --merge`` aligns across machines;
- ``GET /vars``    — process identity and config (boot id, buffer
  version, bind address) for "which incarnation am I talking to";
- ``GET /flight``  — the anomaly flight-recorder ring;
- ``GET /workers`` — the PS's per-worker staleness/contribution ledger
  (``obs.health.StalenessLedger.snapshot``);
- ``GET /alerts``  — the SLO alert engine's rules, active breaches, and
  ordered fired history (each scrape runs one evaluation pass);
- ``GET /meta``    — self-description for fleet federation: role, boot
  id, worker_id, and the served route list (``obs.fleet`` polls this);
- ``GET /history?window=N`` — windowed stats from the process's
  ``HistorySampler`` rings (rates, min/max/last over the trailing N s);
- ``GET /profile`` — device profiling: bare GET for capture status +
  per-device memory watermarks, ``?action=start[&dir=]`` /
  ``?action=stop`` to drive ``jax.profiler`` trace capture remotely;
- ``GET /fleet``   — the merged fleet view, when this process hosts a
  ``FleetAggregator`` (usually the one doing the polling);
- ``GET /replicas`` — the serving router's roster: per-replica
  lifecycle state and dispatch signals, router affinity/requeue
  counters, and the last autoscale decision
  (``serving.fleet.Router.replicas_doc``);
- ``GET /incidents`` — the durable telemetry store's live view: disk
  stats (bytes, segments, last-record age) plus the most recent
  journaled records (``obs.store.TelemetryStore.doc``).

Routes are registered in an explicit table (``_add_route``), and the
full vocabulary lives in the module-level ``ROUTES`` constant —
``scripts/lint_blocking.py`` AST-reads it and rejects unregistered
route strings at ``add_route`` call sites (``# route-ok`` escapes), so
the served surface and the documented surface cannot drift. Unknown
paths answer 404 *with the known-route list in the body*: a scraper
with a typo learns the fix from the error itself.

Security: opsd binds **loopback by default** (``127.0.0.1``). It serves
unauthenticated process internals — trace args can contain request ids
and config values, ``/profile`` can start device captures — so exposing
it beyond the host is an explicit decision: pass ``host=`` or set
``ELEPHAS_OPS_BIND``. This mirrors the PS servers' own
``ELEPHAS_PS_BIND`` convention.

The server is a ``ThreadingHTTPServer`` on a daemon thread: requests
never touch the training/serving hot paths beyond the GIL, handlers
only *read* shared structures (registry exposition and ring snapshots
are already lock-guarded copies), and ``stop()`` is idempotent.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

__all__ = ["OpsServer", "ROUTES"]

#: Registered route vocabulary. Grow this table when adding a route —
#: ``lint_blocking`` rejects ``add_route`` call sites whose path string
#: is not listed here, so every served route is documented by construction.
ROUTES = (
    "/metrics",
    "/healthz",
    "/trace",
    "/vars",
    "/flight",
    "/workers",
    "/alerts",
    "/meta",
    "/history",
    "/profile",
    "/fleet",
    "/shards",
    "/load",
    "/slo",
    "/canary",
    "/replicas",
    "/incidents",
    "/trials",
    "/tenants",
    "/tiers",
    "/rollout",
)


def _default_bind_host() -> str:
    # Loopback unless the operator explicitly opts into exposure.
    return os.environ.get("ELEPHAS_OPS_BIND", "127.0.0.1")


class OpsServer:
    """Mountable introspection endpoint (see module docstring).

    Parameters
    ----------
    port: TCP port; 0 picks a free one (read ``.port`` after
        ``start()``).
    host: bind address; defaults to loopback / ``ELEPHAS_OPS_BIND``.
    registry / tracer / flight: the surfaces to serve; default to the
        process-global ones resolved lazily at request time (so a
        later ``enable_tracing()`` is picked up without a remount).
    role / boot / worker_id: process identity for ``/meta`` and the
        ``elephas_process_info`` stamp on ``/metrics``.
    vars_fn: extra ``/vars`` content, e.g. the PS server's boot id and
        buffer version — called per request so values are live.
    health_fn: extra ``/healthz`` content (membership summary). If it
        raises, ``/healthz`` answers 500 — a health route that lies is
        worse than one that fails.
    workers_fn: the ``/workers`` payload (a staleness-ledger snapshot);
        the route answers an empty table when unset, so scrapers can
        probe any process uniformly.
    alerts_fn: the ``/alerts`` payload (an alert-engine scrape); answers
        an empty rule pack when unset.
    history: a ``HistorySampler`` backing ``/history``; empty shell when
        unset.
    profiler: a ``DeviceProfiler`` backing ``/profile``; a default one
        (jax-backed, tempdir dumps) is created lazily on first use.
    fleet_fn: the ``/fleet`` payload (a ``FleetAggregator.snapshot``);
        empty roster when unset.
    shards_fn: the ``/shards`` payload (a ``ShardGroup.snapshot`` —
        plan digest, directory generation, standby lag, promotions);
        empty doc when unset.
    load_fn: the ``/load`` payload (a ``LoadTracker.snapshot`` — EWMA
        saturation score plus raw signal anatomy); null score when
        unset.
    slo_fn: the ``/slo`` payload (a ``GoodputLedger.snapshot`` —
        objectives, windowed goodput ratios, burn rates); empty
        objective pack when unset.
    canary_fn: the ``/canary`` payload (a ``CanaryDriver.snapshot`` /
        ``PSCanary.snapshot`` — blackbox probe SLIs); zero probes when
        unset.
    replicas_fn: the ``/replicas`` payload (a serving fleet
        ``Router.replicas_doc`` — replica roster + dispatch signals +
        last autoscale decision); empty roster when unset.
    incidents_fn: the ``/incidents`` payload (a ``TelemetryStore.doc``
        — durable-store disk stats + most recent journaled records,
        the live end of the post-mortem plane); empty store when
        unset.
    trials_fn: the ``/trials`` payload (a ``TuneRunner.trials_snapshot``
        — per-trial rung/status/loss cards, rung counts, the search
        digest); empty search when unset.
    tenants_fn: the ``/tenants`` payload (a ``CostLedger.snapshot`` —
        per-tenant token/queue/block-second costs, goodput, and the
        tenancy alert state; routers serve the tenant-wise union over
        their replicas); empty ledger when unset.
    tiers_fn: the ``/tiers`` payload (a ``Router.tiers_doc`` —
        per-tier membership/load/KV pressure, KV-handoff latency and
        failure counts, and the QoS policy card for disaggregated
        prefill/decode serving); empty topology when unset.
    rollout_fn: the ``/rollout`` payload (a ``RolloutController.doc``
        — live-delivery state machine phase, per-replica served
        versions with the canary flagged, the pinned/candidate
        versions, and the rollout event history + digest); idle plane
        when unset.
    """

    def __init__(self, port: int = 0, host: Optional[str] = None,
                 registry=None, tracer=None, flight=None,
                 role: str = "proc", boot: Optional[str] = None,
                 worker_id: Optional[str] = None,
                 vars_fn: Optional[Callable[[], Dict]] = None,
                 health_fn: Optional[Callable[[], Dict]] = None,
                 workers_fn: Optional[Callable[[], Dict]] = None,
                 alerts_fn: Optional[Callable[[], Dict]] = None,
                 history=None, profiler=None,
                 fleet_fn: Optional[Callable[[], Dict]] = None,
                 shards_fn: Optional[Callable[[], Dict]] = None,
                 load_fn: Optional[Callable[[], Dict]] = None,
                 slo_fn: Optional[Callable[[], Dict]] = None,
                 canary_fn: Optional[Callable[[], Dict]] = None,
                 replicas_fn: Optional[Callable[[], Dict]] = None,
                 incidents_fn: Optional[Callable[[], Dict]] = None,
                 trials_fn: Optional[Callable[[], Dict]] = None,
                 tenants_fn: Optional[Callable[[], Dict]] = None,
                 tiers_fn: Optional[Callable[[], Dict]] = None,
                 rollout_fn: Optional[Callable[[], Dict]] = None):
        self._requested_port = port
        self.host = host if host is not None else _default_bind_host()
        self._registry = registry
        self._tracer = tracer
        self._flight = flight
        self.role = role
        self.boot = boot
        self.worker_id = worker_id
        self._vars_fn = vars_fn
        self._health_fn = health_fn
        self._workers_fn = workers_fn
        self._alerts_fn = alerts_fn
        self._history = history
        self._profiler = profiler
        self._fleet_fn = fleet_fn
        self._shards_fn = shards_fn
        self._load_fn = load_fn
        self._slo_fn = slo_fn
        self._canary_fn = canary_fn
        self._replicas_fn = replicas_fn
        self._incidents_fn = incidents_fn
        self._trials_fn = trials_fn
        self._tenants_fn = tenants_fn
        self._tiers_fn = tiers_fn
        self._rollout_fn = rollout_fn
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started_wall = None
        self.port: Optional[int] = None
        # Explicit route table: path → handler(query) -> (code, payload[,
        # content_type]). Every registration is lint-checked against the
        # module ROUTES vocabulary.
        self._routes: Dict[str, Callable] = {}
        self._add_route("/metrics", self._h_metrics)
        self._add_route("/healthz", self._h_healthz)
        self._add_route("/trace", self._h_trace)
        self._add_route("/vars", self._h_vars)
        self._add_route("/flight", self._h_flight)
        self._add_route("/workers", self._h_workers)
        self._add_route("/alerts", self._h_alerts)
        self._add_route("/meta", self._h_meta)
        self._add_route("/history", self._h_history)
        self._add_route("/profile", self._h_profile)
        self._add_route("/fleet", self._h_fleet)
        self._add_route("/shards", self._h_shards)
        self._add_route("/load", self._h_load)
        self._add_route("/slo", self._h_slo)
        self._add_route("/canary", self._h_canary)
        self._add_route("/replicas", self._h_replicas)
        self._add_route("/incidents", self._h_incidents)
        self._add_route("/trials", self._h_trials)
        self._add_route("/tenants", self._h_tenants)
        self._add_route("/tiers", self._h_tiers)
        self._add_route("/rollout", self._h_rollout)

    def _add_route(self, path: str, handler: Callable) -> None:
        self._routes[path] = handler

    def routes(self) -> Tuple[str, ...]:
        """The served route list (sorted) — ``/meta`` and 404 bodies."""
        return tuple(sorted(self._routes))

    # Lazy resolution: a tracer enabled after mount is still served.
    def _get_registry(self):
        if self._registry is not None:
            return self._registry
        from elephas_tpu import obs
        return obs.default_registry()

    def _get_tracer(self):
        if self._tracer is not None:
            return self._tracer
        from elephas_tpu import obs
        return obs.default_tracer()

    def _get_flight(self):
        if self._flight is not None:
            return self._flight
        from elephas_tpu import obs
        return obs.default_flight_recorder()

    def _get_profiler(self):
        if self._profiler is None:
            from elephas_tpu.obs.devprof import DeviceProfiler
            self._profiler = DeviceProfiler()
        return self._profiler

    # -- route handlers: (query) -> (code, payload[, content_type]) ---------

    def _proc_info_line(self) -> str:
        """The process-identity stamp appended to every ``/metrics``
        body: merged fleet dumps stay attributable per sample source."""
        boot = self.boot or ""
        return (
            "# TYPE elephas_process_info gauge\n"
            f'elephas_process_info{{role="{self.role}",boot="{boot}",'
            f'pid="{os.getpid()}"}} 1\n'
        )

    def _h_metrics(self, query):
        text = self._get_registry().expose_text() + self._proc_info_line()
        return 200, text.encode(), "text/plain; version=0.0.4"

    def _h_healthz(self, query):
        doc = {"status": "ok",
               "uptime_s": time.time() - self._started_wall}
        if self._health_fn is not None:
            doc.update(self._health_fn())
        return 200, doc

    def _h_trace(self, query):
        return 200, self._get_tracer().export_chrome()

    def _h_vars(self, query):
        doc = {"pid": os.getpid(),
               "ops_host": self.host,
               "ops_port": self.port}
        if self._vars_fn is not None:
            doc.update(self._vars_fn())
        return 200, doc

    def _h_flight(self, query):
        return 200, self._get_flight().snapshot()

    def _h_workers(self, query):
        if self._workers_fn is not None:
            return 200, self._workers_fn()
        return 200, {"workers": {}, "total_updates": 0,
                     "unstamped_updates": 0}

    def _h_alerts(self, query):
        if self._alerts_fn is not None:
            return 200, self._alerts_fn()
        return 200, {"rules": [], "active": [], "fired": [],
                     "fired_kinds": []}

    def _h_meta(self, query):
        return 200, {
            "role": self.role,
            "boot": self.boot,
            "worker_id": self.worker_id,
            "pid": os.getpid(),
            "ops_host": self.host,
            "ops_port": self.port,
            "routes": list(self.routes()),
        }

    def _h_history(self, query):
        window = query.get("window")
        window_s = float(window) if window else None
        if self._history is None:
            return 200, {"period_s": None, "capacity": 0,
                         "window_s": window_s, "ticks": 0, "series": {}}
        return 200, self._history.snapshot(window_s=window_s)

    def _h_profile(self, query):
        from elephas_tpu.obs import devprof

        action = query.get("action")
        prof = self._get_profiler()
        if action is None:
            return 200, {"profiler": prof.status(),
                         "device_memory": devprof.device_memory_snapshot()}
        if action == "start":
            doc = prof.start(out_dir=query.get("dir"))
            code = {"started": 200, "busy": 409}.get(doc["status"], 500)
            return code, doc
        if action == "stop":
            doc = prof.stop()
            return (200 if doc["status"] in ("stopped", "idle")
                    else 500), doc
        return 400, {"error": f"unknown action {action!r}",
                     "actions": ["start", "stop"]}

    def _h_fleet(self, query):
        if self._fleet_fn is not None:
            return 200, self._fleet_fn()
        return 200, {"polls": 0, "status_counts": {}, "processes": {}}

    def _h_shards(self, query):
        if self._shards_fn is not None:
            return 200, self._shards_fn()
        return 200, {"plan": None, "directory": None, "standbys": [],
                     "promotions": []}

    def _h_load(self, query):
        if self._load_fn is not None:
            return 200, self._load_fn()
        return 200, {"score": None, "raw": None, "observations": 0,
                     "signals": None}

    def _h_slo(self, query):
        if self._slo_fn is not None:
            return 200, self._slo_fn()
        return 200, {"objectives": [], "evaluated": 0, "goodput": {},
                     "burn": {}, "goodput_ratio": None}

    def _h_canary(self, query):
        if self._canary_fn is not None:
            return 200, self._canary_fn()
        return 200, {"surface": None, "probes": 0, "failures": 0,
                     "failure_ratio": None, "last": None}

    def _h_replicas(self, query):
        if self._replicas_fn is not None:
            return 200, self._replicas_fn()
        return 200, {"replicas": {}, "router": None, "autoscale": None}

    def _h_incidents(self, query):
        if self._incidents_fn is not None:
            return 200, self._incidents_fn()
        return 200, {"meta": None, "recent": []}

    def _h_trials(self, query):
        if self._trials_fn is not None:
            return 200, self._trials_fn()
        return 200, {"counts": {}, "trials": {}, "best": None,
                     "search_digest": None, "epochs_spent": 0}

    def _h_tenants(self, query):
        if self._tenants_fn is not None:
            return 200, self._tenants_fn()
        return 200, {"tenants": {}, "totals": {}, "kv_share": {},
                     "alerts": {"active": [], "fired": [],
                                "fired_kinds": []}}

    def _h_tiers(self, query):
        if self._tiers_fn is not None:
            return 200, self._tiers_fn()
        return 200, {"disagg_active": False, "tiers": {},
                     "imbalance": 0.0,
                     "handoffs": {"count": 0, "fails": 0,
                                  "p50_ms": None, "p99_ms": None},
                     "preemptions": 0, "qos": None}

    def _h_rollout(self, query):
        if self._rollout_fn is not None:
            return 200, self._rollout_fn()
        return 200, {"active": False, "phase": "idle",
                     "approved_version": None, "candidate_version": None,
                     "canary": None, "versions": {}, "skew": 0,
                     "events": [], "digest": None}

    def start(self) -> "OpsServer":
        if self._httpd is not None:
            return self
        ops = self
        self._started_wall = time.time()

        class Handler(BaseHTTPRequestHandler):
            # opsd must never spam the process stdout per scrape.
            def log_message(self, *a):  # noqa: D102
                pass

            def _send(self, code: int, body: bytes,
                      content_type: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, doc) -> None:
                self._send(code, json.dumps(doc).encode())

            def do_GET(self):  # noqa: N802
                try:
                    split = urllib.parse.urlsplit(self.path)
                    handler = ops._routes.get(split.path)
                    if handler is None:
                        self._send_json(404, {
                            "error": "not found",
                            "path": split.path,
                            "routes": list(ops.routes()),
                        })
                        return
                    query = {k: v[-1] for k, v in
                             urllib.parse.parse_qs(split.query).items()}
                    result = handler(query)
                    if len(result) == 3:
                        code, payload, ctype = result
                        self._send(code, payload, ctype)
                    else:
                        code, payload = result
                        self._send_json(code, payload)
                except Exception as exc:  # surface, don't hang the scrape
                    try:
                        self._send_json(500, {"error": repr(exc)})
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"opsd:{self.port}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
