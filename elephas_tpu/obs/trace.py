"""Span tracer: bounded in-memory ring buffer → Chrome ``trace_event``.

The serving/training hot paths need per-phase wall-clock visibility
(PR 2's single ``dispatch_to_fetch_s`` gauge steered a 15× win — this
generalizes it) without ever touching the device: recording a span is a
clock read plus one append into a ``deque(maxlen=...)`` ring, so it can
stay on inside the pipelined scheduler's overlap window. The ring drops
the OLDEST events when full — a long-running server keeps the recent
past instead of dying or growing without bound.

Two recording styles, one event format:

- ``with tracer.span("prefill", req_id=3):`` — reads the tracer's clock
  on enter/exit (training loops, parameter-server push/pull);
- ``tracer.record("queue", begin_s, end_s, track="req:3")`` — a span
  whose endpoints the CALLER already timestamped with the same clock
  (the serving scheduler, whose injectable ``clock`` the fake-clock
  tests replace — pass that clock to the ``Tracer`` so both styles land
  in one time domain).

Export is Chrome ``trace_event`` JSON (``{"traceEvents": [...]}``),
viewable in Perfetto / ``chrome://tracing``. Each distinct ``track``
becomes a named thread row, so per-request spans (``track="req:7"``)
render as one lane per request with phases nested by containment —
``scripts/trace_report.py`` reads the same file back into per-phase
percentiles and a request tree.

Device correlation: when ``annotate_device=True`` (default) every
``span()`` also enters ``jax.profiler.TraceAnnotation``, so if a
``jax.profiler`` trace window is open (``metrics.logging.trace``) the
SAME span names appear on the host rows of the device trace, lined up
with the XLA ops they caused. The annotation is a no-op outside a
profiler window — cost is one small object.

Disabled tracers are free: ``span()`` returns a shared null context
(no allocation), ``record``/``instant`` return before touching the
clock. ``NULL_TRACER`` is the module's shared disabled instance —
instrumented code can hold it unconditionally.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["SpanEvent", "Tracer", "NULL_TRACER"]

_NULL_CTX = contextlib.nullcontext()  # shared: disabled span() allocates nothing


class SpanEvent:
    """One recorded span (or instant, when ``end_s == begin_s``)."""

    __slots__ = ("name", "begin_s", "end_s", "track", "args")

    def __init__(self, name: str, begin_s: float, end_s: float,
                 track: Optional[str], args: Optional[Dict[str, Any]]):
        self.name = name
        self.begin_s = begin_s
        self.end_s = end_s
        self.track = track
        self.args = args

    @property
    def duration_s(self) -> float:
        return self.end_s - self.begin_s

    def __repr__(self):
        return (f"SpanEvent({self.name!r}, {self.begin_s:.6f}→"
                f"{self.end_s:.6f}, track={self.track!r})")


class _Span:
    """Live ``span()`` context — clock on enter, ring append on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_begin", "_annotation")

    def __init__(self, tracer: "Tracer", name: str, args):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._begin = 0.0
        self._annotation = None

    def __enter__(self):
        tracer = self._tracer
        if tracer._annotate:
            annotation = tracer._device_annotation(self._name)
            if annotation is not None:
                self._annotation = annotation
                annotation.__enter__()
        self._begin = tracer.clock()
        return self

    def note(self, **attrs) -> "_Span":
        """Attach args discovered mid-span (payload bytes, codec, cache
        hit) — merged into the event's ``args`` at exit. Callers using
        ``with tracer.span(...) as sp:`` must guard for a disabled
        tracer, whose null context yields ``None``."""
        if self._args is None:
            self._args = dict(attrs)
        else:
            self._args.update(attrs)
        return self

    def __exit__(self, *exc):
        tracer = self._tracer
        end = tracer.clock()
        if self._annotation is not None:
            self._annotation.__exit__(*exc)
        # Track = recording thread: async trainer workers are threads,
        # so each worker's pull/train/push phases get their own row.
        tracer._events.append(
            SpanEvent(self._name, self._begin, end,
                      threading.current_thread().name, self._args)
        )
        return False


class Tracer:
    """Bounded host-side span recorder.

    Parameters
    ----------
    capacity: ring size in events; the oldest are dropped when full.
    clock: monotonic seconds source. MUST match the clock of any caller
        that records retroactive spans (``record``) — the serving engine
        passes its own injectable clock through.
    enabled: a disabled tracer records nothing and ``span()`` returns a
        shared null context (zero allocation).
    annotate_device: bridge each ``span()`` into
        ``jax.profiler.TraceAnnotation`` so host spans line up with XLA
        ops inside an open profiler trace window.
    """

    def __init__(self, capacity: int = 65536, clock=time.monotonic,
                 enabled: bool = True, annotate_device: bool = True):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {capacity}")
        self.capacity = capacity
        self.clock = clock
        self.enabled = enabled
        self._annotate = annotate_device
        self._events: deque = deque(maxlen=capacity)
        self._annotation_cls = None  # resolved lazily (jax import)

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **args):
        """Context manager recording ``name`` from enter to exit."""
        if not self.enabled:
            return _NULL_CTX
        return _Span(self, name, args or None)

    def record(self, name: str, begin_s: float, end_s: float,
               track: Optional[str] = None, **args) -> None:
        """Record a span whose endpoints the caller already timestamped
        (with THIS tracer's clock domain)."""
        if not self.enabled:
            return
        if track is None:
            track = threading.current_thread().name
        self._events.append(
            SpanEvent(name, begin_s, end_s, track, args or None)
        )

    def instant(self, name: str, at: Optional[float] = None,
                track: Optional[str] = None, **args) -> None:
        """Zero-duration marker (defaults to now)."""
        if not self.enabled:
            return
        t = self.clock() if at is None else at
        if track is None:
            track = threading.current_thread().name
        self._events.append(SpanEvent(name, t, t, track, args or None))

    def _device_annotation(self, name: str):
        """A ``jax.profiler.TraceAnnotation`` for ``name``, or None when
        jax (or the annotation API) is unavailable — the tracer must
        work in stripped environments."""
        if self._annotation_cls is None:
            try:
                from jax.profiler import TraceAnnotation
                self._annotation_cls = TraceAnnotation
            except Exception:  # no jax / no profiler: disable the bridge
                self._annotate = False
                return None
        try:
            return self._annotation_cls(name)
        except Exception:
            self._annotate = False
            return None

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        return len(self._events)

    def events(self) -> List[SpanEvent]:
        """Snapshot of the ring (oldest first)."""
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()

    # -- export ------------------------------------------------------------

    def to_chrome_events(self) -> List[dict]:
        """The ring as Chrome ``trace_event`` dicts (microsecond ts,
        normalized so the earliest event sits at t=0).

        Each distinct ``track`` becomes one named tid row (thread-name
        metadata events included), untracked spans share a row per
        recording thread name; Perfetto nests spans on a row by time
        containment.
        """
        events = self.events()
        if not events:
            return []
        t0 = min(e.begin_s for e in events)
        tids: Dict[str, int] = {}
        out: List[dict] = []

        def tid_for(track: str) -> int:
            if track not in tids:
                tids[track] = len(tids) + 1
                out.append({
                    "name": "thread_name", "ph": "M", "pid": 0,
                    "tid": tids[track], "args": {"name": track},
                })
            return tids[track]

        main = threading.main_thread().name
        for e in events:
            rec = {
                "name": e.name,
                "ph": "X",
                "pid": 0,
                "tid": tid_for(e.track if e.track is not None else main),
                "ts": (e.begin_s - t0) * 1e6,
                "dur": max(e.end_s - e.begin_s, 0.0) * 1e6,
            }
            if e.args:
                rec["args"] = dict(e.args)
            out.append(rec)
        return out

    def export_chrome(self, path: Optional[str] = None):
        """Dump the ring as a Perfetto-viewable trace. Returns the
        ``{"traceEvents": [...]}`` dict; also writes it to ``path``
        when given."""
        doc = {
            "traceEvents": self.to_chrome_events(),
            "displayTimeUnit": "ms",
        }
        if path is not None:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc


#: Shared disabled instance — hold it unconditionally in instrumented code.
NULL_TRACER = Tracer(capacity=0, enabled=False, annotate_device=False)
