"""Span tracer: bounded in-memory ring buffer → Chrome ``trace_event``.

The serving/training hot paths need per-phase wall-clock visibility
(PR 2's single ``dispatch_to_fetch_s`` gauge steered a 15× win — this
generalizes it) without ever touching the device: recording a span is a
clock read plus one append into a ``deque(maxlen=...)`` ring, so it can
stay on inside the pipelined scheduler's overlap window. The ring drops
the OLDEST events when full — a long-running server keeps the recent
past instead of dying or growing without bound.

Two recording styles, one event format:

- ``with tracer.span("prefill", req_id=3):`` — reads the tracer's clock
  on enter/exit (training loops, parameter-server push/pull);
- ``tracer.record("queue", begin_s, end_s, track="req:3")`` — a span
  whose endpoints the CALLER already timestamped with the same clock
  (the serving scheduler, whose injectable ``clock`` the fake-clock
  tests replace — pass that clock to the ``Tracer`` so both styles land
  in one time domain).

Export is Chrome ``trace_event`` JSON (``{"traceEvents": [...]}``),
viewable in Perfetto / ``chrome://tracing``. Each distinct ``track``
becomes a named thread row, so per-request spans (``track="req:7"``)
render as one lane per request with phases nested by containment —
``scripts/trace_report.py`` reads the same file back into per-phase
percentiles and a request tree.

Device correlation: when ``annotate_device=True`` (default) every
``span()`` also enters ``jax.profiler.TraceAnnotation``, so if a
``jax.profiler`` trace window is open (``metrics.logging.trace``) the
SAME span names appear on the host rows of the device trace, lined up
with the XLA ops they caused. The annotation is a no-op outside a
profiler window — cost is one small object.

Disabled tracers are free: ``span()`` returns a shared null context
(no allocation), ``record``/``instant`` return before touching the
clock. ``NULL_TRACER`` is the module's shared disabled instance —
instrumented code can hold it unconditionally.

Distributed trace context (this PR): every live span carries a
``(trace_id, span_id, parent_id)`` triple threaded through a
``contextvars.ContextVar`` — nested spans on one thread become a causal
tree automatically, ``activate(ctx)`` adopts a context that crossed a
thread (the async comms pipeline) or a socket (the parameter-server
wire codec ships the pair in its header), and ``new_context()`` roots a
fresh trace (the async trainer roots one per (epoch, partition) unit).
Ids are strings: an 8-hex per-process prefix + a counter for span ids
(one contextvar op + one format per span — cheap enough for the <2%
serving-overhead guardrail) and 16 random hex chars for trace ids
(minted once per unit/request, not per span).

Truncation honesty: a bounded ring that silently overwrites unexported
spans makes ``trace_report.py`` lie by omission, so every overwrite is
counted — ``Tracer.dropped`` locally and ``tracer_dropped_spans_total``
on the process registry (lazily bound to dodge the obs import cycle).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional

__all__ = [
    "SpanEvent",
    "TraceContext",
    "Tracer",
    "NULL_TRACER",
    "activate",
    "current_context",
    "new_context",
    "new_span_id",
    "new_trace_id",
    "export_events",
]

_NULL_CTX = contextlib.nullcontext()  # shared: disabled span() allocates nothing


class TraceContext(NamedTuple):
    """The active span's identity: what a child (local or remote) points
    at as its parent. Exactly the pair the wire codec ships."""

    trace_id: str
    span_id: str


#: The innermost active span on this thread/task (None = no trace).
_CTX: contextvars.ContextVar[Optional[TraceContext]] = contextvars.ContextVar(
    "elephas_trace_ctx", default=None
)

# Span ids: per-process random prefix + counter — unique across the
# processes of one job without per-span urandom (which would cost a
# syscall inside the serving hot path).
_SPAN_PREFIX = os.urandom(4).hex()
_SPAN_COUNTER = itertools.count(1)


def new_span_id() -> str:
    return f"{_SPAN_PREFIX}{next(_SPAN_COUNTER):x}"


def new_trace_id() -> str:
    """A fresh 16-hex trace id (minted per unit/request, not per span)."""
    return os.urandom(8).hex()


def new_context() -> TraceContext:
    """A fresh root context — activate it around a unit of work so every
    span recorded inside (this thread, adopted threads, remote handlers)
    lands in one causal tree."""
    return TraceContext(new_trace_id(), new_span_id())


def current_context() -> Optional[TraceContext]:
    """The innermost active span's ``(trace_id, span_id)``, or None."""
    return _CTX.get()


class activate:
    """Context manager installing ``ctx`` as the active trace context
    (and restoring the previous one on exit). ``ctx=None`` detaches —
    spans recorded inside start fresh traces.

    Used to adopt a context that crossed a boundary contextvars can't:
    a queue hop to the comms thread, or a wire frame into a PS handler.
    Reentrant-safe via contextvar tokens; allocation is one small object
    per adoption (never per span)."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        self._token = _CTX.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _CTX.reset(self._token)
        return False


class SpanEvent:
    """One recorded span (or instant, when ``end_s == begin_s``)."""

    __slots__ = ("name", "begin_s", "end_s", "track", "args",
                 "trace_id", "span_id", "parent_id")

    def __init__(self, name: str, begin_s: float, end_s: float,
                 track: Optional[str], args: Optional[Dict[str, Any]],
                 trace_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 parent_id: Optional[str] = None):
        self.name = name
        self.begin_s = begin_s
        self.end_s = end_s
        self.track = track
        self.args = args
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    @property
    def duration_s(self) -> float:
        return self.end_s - self.begin_s

    def __repr__(self):
        return (f"SpanEvent({self.name!r}, {self.begin_s:.6f}→"
                f"{self.end_s:.6f}, track={self.track!r})")


class _Span:
    """Live ``span()`` context — clock on enter, ring append on exit.

    When a trace context is active (or always, for the span tree on one
    thread), the span mints its own id, records the enclosing span as
    parent, and installs itself as the active context so children —
    including remote PS handle spans fed the wire-propagated pair —
    point back at it."""

    __slots__ = ("_tracer", "_name", "_args", "_begin", "_annotation",
                 "_trace_id", "_span_id", "_parent_id", "_token")

    def __init__(self, tracer: "Tracer", name: str, args):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._begin = 0.0
        self._annotation = None
        self._trace_id = None
        self._span_id = None
        self._parent_id = None
        self._token = None

    def __enter__(self):
        tracer = self._tracer
        if tracer._annotate:
            annotation = tracer._device_annotation(self._name)
            if annotation is not None:
                self._annotation = annotation
                annotation.__enter__()
        parent = _CTX.get()
        if parent is not None:
            self._trace_id = parent.trace_id
            self._parent_id = parent.span_id
            self._span_id = new_span_id()
            self._token = _CTX.set(TraceContext(parent.trace_id,
                                                self._span_id))
        self._begin = tracer.clock()
        return self

    @property
    def context(self) -> Optional[TraceContext]:
        """This span's ``(trace_id, span_id)`` — what the client ships
        on the wire so the server-side handle span becomes its child.
        None when no trace is active."""
        if self._span_id is None:
            return None
        return TraceContext(self._trace_id, self._span_id)

    def note(self, **attrs) -> "_Span":
        """Attach args discovered mid-span (payload bytes, codec, cache
        hit) — merged into the event's ``args`` at exit. Callers using
        ``with tracer.span(...) as sp:`` must guard for a disabled
        tracer, whose null context yields ``None``."""
        if self._args is None:
            self._args = dict(attrs)
        else:
            self._args.update(attrs)
        return self

    def __exit__(self, *exc):
        tracer = self._tracer
        end = tracer.clock()
        if self._annotation is not None:
            self._annotation.__exit__(*exc)
        if self._token is not None:
            _CTX.reset(self._token)
        # Track = recording thread: async trainer workers are threads,
        # so each worker's pull/train/push phases get their own row.
        tracer._append(
            SpanEvent(self._name, self._begin, end,
                      threading.current_thread().name, self._args,
                      self._trace_id, self._span_id, self._parent_id)
        )
        return False


class Tracer:
    """Bounded host-side span recorder.

    Parameters
    ----------
    capacity: ring size in events; the oldest are dropped when full.
    clock: monotonic seconds source. MUST match the clock of any caller
        that records retroactive spans (``record``) — the serving engine
        passes its own injectable clock through.
    enabled: a disabled tracer records nothing and ``span()`` returns a
        shared null context (zero allocation).
    annotate_device: bridge each ``span()`` into
        ``jax.profiler.TraceAnnotation`` so host spans line up with XLA
        ops inside an open profiler trace window.
    """

    def __init__(self, capacity: int = 65536, clock=time.monotonic,
                 enabled: bool = True, annotate_device: bool = True):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {capacity}")
        self.capacity = capacity
        self.clock = clock
        self.enabled = enabled
        self._annotate = annotate_device
        self._events: deque = deque(maxlen=capacity)
        self._annotation_cls = None  # resolved lazily (jax import)
        self.dropped = 0  # ring overwrites of unexported spans
        self._dropped_counter = None  # lazily bound registry counter
        self._stores: tuple = ()  # durable tees (obs/store.py), COW

    def _append(self, event: SpanEvent) -> None:
        events = self._events
        if len(events) == events.maxlen:
            # The append below overwrites the oldest unexported span —
            # count it so trace_report can't silently lie by omission.
            self.dropped += 1
            counter = self._dropped_counter
            if counter is None:
                try:
                    from elephas_tpu import obs  # lazy: import cycle
                    counter = obs.default_registry().counter(
                        "tracer_dropped_spans_total",
                        "Spans overwritten by the bounded ring before export.",
                    )
                except Exception:
                    counter = False  # registry unavailable: count locally
                self._dropped_counter = counter
            if counter:
                counter.inc()
        events.append(event)
        # Durable tee: every COMPLETED span summary (this is the single
        # sink — __exit__, record(), instant() all land here) journals
        # so a post-mortem keeps the recent span history the ring loses
        # with the process. Summaries only: name/duration/ids, no args
        # beyond what the incident timeline needs.
        for store in self._stores:
            try:
                store.record_span(
                    {"name": event.name, "begin_s": event.begin_s,
                     "dur_s": event.end_s - event.begin_s,
                     "track": event.track, "trace_id": event.trace_id},
                    mono_s=event.end_s,
                )
            except Exception:
                pass

    # -- durable tee -------------------------------------------------------

    def attach_store(self, store) -> None:
        """Journal every subsequent completed span summary into
        ``store`` (a ``TelemetryStore``). Idempotent."""
        if store not in self._stores:
            self._stores = self._stores + (store,)

    def detach_store(self, store) -> None:
        self._stores = tuple(s for s in self._stores if s is not store)

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **args):
        """Context manager recording ``name`` from enter to exit."""
        if not self.enabled:
            return _NULL_CTX
        return _Span(self, name, args or None)

    def record(self, name: str, begin_s: float, end_s: float,
               track: Optional[str] = None, **args) -> None:
        """Record a span whose endpoints the caller already timestamped
        (with THIS tracer's clock domain). Tagged with the active trace
        context (as a leaf: the retroactive span never becomes a parent,
        so the serving hot path pays one contextvar read, no id mint)."""
        if not self.enabled:
            return
        if track is None:
            track = threading.current_thread().name
        ctx = _CTX.get()
        if ctx is None:
            event = SpanEvent(name, begin_s, end_s, track, args or None)
        else:
            event = SpanEvent(name, begin_s, end_s, track, args or None,
                              ctx.trace_id, new_span_id(), ctx.span_id)
        self._append(event)

    def instant(self, name: str, at: Optional[float] = None,
                track: Optional[str] = None, **args) -> None:
        """Zero-duration marker (defaults to now)."""
        if not self.enabled:
            return
        t = self.clock() if at is None else at
        if track is None:
            track = threading.current_thread().name
        ctx = _CTX.get()
        if ctx is None:
            event = SpanEvent(name, t, t, track, args or None)
        else:
            event = SpanEvent(name, t, t, track, args or None,
                              ctx.trace_id, new_span_id(), ctx.span_id)
        self._append(event)

    def _device_annotation(self, name: str):
        """A ``jax.profiler.TraceAnnotation`` for ``name``, or None when
        jax (or the annotation API) is unavailable — the tracer must
        work in stripped environments."""
        if self._annotation_cls is None:
            try:
                from jax.profiler import TraceAnnotation
                self._annotation_cls = TraceAnnotation
            except Exception:  # no jax / no profiler: disable the bridge
                self._annotate = False
                return None
        try:
            return self._annotation_cls(name)
        except Exception:
            self._annotate = False
            return None

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        return len(self._events)

    def events(self) -> List[SpanEvent]:
        """Snapshot of the ring (oldest first)."""
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()

    # -- export ------------------------------------------------------------

    def to_chrome_events(self) -> List[dict]:
        """The ring as Chrome ``trace_event`` dicts (microsecond ts,
        normalized so the earliest event sits at t=0).

        Each distinct ``track`` becomes one named tid row (thread-name
        metadata events included), untracked spans share a row per
        recording thread name; Perfetto nests spans on a row by time
        containment. Spans recorded under a trace context carry
        ``trace_id``/``span_id``/``parent_id`` in ``args`` — the keys
        ``trace_report.py --merge`` joins on across processes.
        """
        return _to_chrome_events(self.events())

    def export_chrome(self, path: Optional[str] = None,
                      process: Optional[str] = None):
        """Dump the ring as a Perfetto-viewable trace. Returns the
        ``{"traceEvents": [...]}`` dict; also writes it to ``path``
        when given.

        The doc carries a ``clockSync`` block — the normalization origin
        in this tracer's clock domain plus a (mono, wall) sample taken
        at export — so ``trace_report.py --merge`` can map every event
        back to wall time and align dumps from different processes
        (each with its own arbitrary monotonic-clock base).
        """
        return export_events(self.events(), self.clock, path=path,
                             process=process, dropped=self.dropped)


def _to_chrome_events(events: List[SpanEvent]) -> List[dict]:
    if not events:
        return []
    t0 = min(e.begin_s for e in events)
    tids: Dict[str, int] = {}
    out: List[dict] = []

    def tid_for(track: str) -> int:
        if track not in tids:
            tids[track] = len(tids) + 1
            out.append({
                "name": "thread_name", "ph": "M", "pid": 0,
                "tid": tids[track], "args": {"name": track},
            })
        return tids[track]

    main = threading.main_thread().name
    for e in events:
        rec = {
            "name": e.name,
            "ph": "X",
            "pid": 0,
            "tid": tid_for(e.track if e.track is not None else main),
            "ts": (e.begin_s - t0) * 1e6,
            "dur": max(e.end_s - e.begin_s, 0.0) * 1e6,
        }
        if e.args:
            rec["args"] = dict(e.args)
        if e.trace_id is not None:
            args = rec.setdefault("args", {})
            args["trace_id"] = e.trace_id
            args["span_id"] = e.span_id
            if e.parent_id is not None:
                args["parent_id"] = e.parent_id
        out.append(rec)
    return out


def export_events(events: List[SpanEvent], clock,
                  path: Optional[str] = None,
                  process: Optional[str] = None,
                  dropped: int = 0):
    """Build (and optionally write) a Chrome-trace doc for an event
    subset — ``chaos_bench --trace`` splits one in-process ring into
    per-role dumps (workers vs PS handlers) through this.

    ``clock`` must be the clock the events were recorded with; it is
    sampled once alongside wall time to form the ``clockSync`` block.
    """
    doc = {
        "traceEvents": _to_chrome_events(events),
        "displayTimeUnit": "ms",
        "clockSync": {
            # t=0 of the normalized events, in the recording clock:
            "origin_mono_s": (min(e.begin_s for e in events)
                              if events else 0.0),
            # simultaneous sample pair mapping that clock to wall time:
            "mono_s_at_export": clock(),
            "wall_s_at_export": time.time(),
        },
        "droppedSpans": dropped,
    }
    if process is not None:
        doc["process"] = process
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc


#: Shared disabled instance — hold it unconditionally in instrumented code.
NULL_TRACER = Tracer(capacity=0, enabled=False, annotate_device=False)
