"""Per-tenant cost attribution: who is spending the fleet's resources.

Every signal the observability arc built so far — load, SLO/goodput,
flight, fleet federation — is per-process or per-request; none of it
knows *who* is asking. The ROADMAP's multi-tenant QoS item needs that
answer before any admission policy can exist: you cannot fair-share
what you cannot attribute. This module is the accounting half:

- A ``tenant=`` tag enters at ``Router.submit`` / ``InferenceEngine.
  submit``, rides the ``Request`` object through the continuous-
  batching scheduler, the spec-decode harvest, and the router's
  requeue-on-death path (the tag lives in the assignment kwargs the
  requeue replays, so attribution survives mid-flight kills), and every
  cost site bills the tag's ``CostLedger`` row.
- ``CostLedger`` — per-tenant prefill vs decode tokens, queue seconds,
  KV **block-seconds** (integrated from ``PagedKVPool`` block occupancy
  per owner slot — the resource that actually saturates, so a noisy
  neighbor is visible in the unit it is stealing), spec-decode
  draft/accept counts, requeues, and terminal statuses. Untagged
  requests bill the ``"default"`` tenant — shared cost is still cost.
- A per-tenant view of the PR 10 ``GoodputLedger``: each tenant gets
  its own windowed goodput/burn ledger (mirrored into private
  registries so the process-global ``serving_goodput_burn{objective=}``
  family keeps its schema), rolled up as
  ``serving_tenant_goodput_burn{objective=,tenant=}`` gauges plus a
  synthetic ``serving_goodput_burn{objective=,tenant=}`` metrics view
  (the ``_BurnMetricsView`` idiom from ``serving/fleet/replica.py``)
  that the tenancy alert pack evaluates: ``tenant_burn_high`` latches
  per tenant, and ``noisy_neighbor`` fires when one tenant holds more
  than ``NOISY_KV_SHARE`` of the pool's integrated block-seconds while
  at least one other tenant is paying for blocks too.

Read-out paths: ``snapshot()`` is the opsd ``/tenants`` document;
``merge_tenant_docs`` unions per-replica documents tenant-wise (counters
sum; goodput takes the worst burn / min ratio — a fleet-total burn
would be a lie) for the router's ``/tenants`` route and the
``FleetAggregator``'s fleet view; ``scripts/fleet_top.py`` renders the
TENANTS board from either.

Conservation is the design invariant the bench gates: token emission is
billed incrementally at the harvest sites, yet the sum over tenants of
``decode_tokens`` must equal ``ServingMetrics.tokens_out`` (counted
independently at finish from ``len(result.tokens)``), and the sum of
``prefill_tokens`` must equal the prompt tokens admitted. Attribution
that leaks under churn (kills, evictions, requeues) shows up as a
conservation failure, not a silent mis-bill.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from elephas_tpu.obs.alerts import AlertEngine, AlertRule
from elephas_tpu.obs.registry import MetricsRegistry
from elephas_tpu.obs.slo import GoodputLedger, SLOObjective
from elephas_tpu.utils import locksan

__all__ = [
    "CostLedger",
    "DEFAULT_TENANT",
    "NOISY_KV_SHARE",
    "TenantCosts",
    "merge_tenant_docs",
    "tenant_rules",
]

#: The tenant every untagged request bills. A real name, not a None:
#: shared cost rendered as a row is attributable; dropped cost is not.
DEFAULT_TENANT = "default"

#: ``noisy_neighbor`` threshold: the fraction of the pool's integrated
#: block-seconds one tenant must hold — while at least one *other*
#: tenant also holds blocks — for the alert to fire. A single-tenant
#: engine never has a neighbor to be noisy to.
NOISY_KV_SHARE = 0.75

#: Burn threshold for the per-tenant latched alert; matches the
#: fleet-wide ``goodput_burn_high`` working point (budget parity).
TENANT_BURN_WARN = 1.0


class TenantCosts:
    """One tenant's mutable cost row (plain counters, no lock — the
    owning ``CostLedger`` serializes access)."""

    __slots__ = (
        "submitted", "completed", "timed_out", "rejected", "requeues",
        "prefill_tokens", "cached_prefill_tokens", "decode_tokens",
        "queue_seconds", "kv_block_seconds", "cow_copies",
        "spec_windows", "spec_drafted", "spec_accepted", "spec_emitted",
    )

    def __init__(self):
        self.submitted = 0
        self.completed = 0
        self.timed_out = 0
        self.rejected = 0
        self.requeues = 0
        self.prefill_tokens = 0
        self.cached_prefill_tokens = 0
        self.decode_tokens = 0
        self.queue_seconds = 0.0
        self.kv_block_seconds = 0.0
        self.cow_copies = 0
        self.spec_windows = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_emitted = 0

    def to_dict(self) -> Dict[str, Any]:
        drafted = self.spec_drafted
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "timed_out": self.timed_out,
            "rejected": self.rejected,
            "requeues": self.requeues,
            "prefill_tokens": self.prefill_tokens,
            "cached_prefill_tokens": self.cached_prefill_tokens,
            "decode_tokens": self.decode_tokens,
            "queue_seconds": self.queue_seconds,
            "kv_block_seconds": self.kv_block_seconds,
            "cow_copies": self.cow_copies,
            "spec": {
                "windows": self.spec_windows,
                "drafted": drafted,
                "accepted": self.spec_accepted,
                "emitted": self.spec_emitted,
                "accept_rate": (self.spec_accepted / drafted
                                if drafted else None),
            },
        }


def tenant_rules() -> List[AlertRule]:
    """The tenancy alert pack, evaluated against the ledger's synthetic
    metrics view (see ``CostLedger.metrics_view``)."""
    return [
        # Per-tenant multi-window burn at budget parity: the same
        # AND-gate semantics as goodput_burn_high, latched per
        # (objective, tenant) child via the prefix match.
        AlertRule("tenant_burn_high", "serving_goodput_burn",
                  ">", TENANT_BURN_WARN, kind="tenant_burn",
                  severity="warn"),
        # One tenant is holding most of the KV pool's block-seconds
        # while somebody else is also paying for blocks: the classic
        # noisy neighbor, measured in the resource that saturates.
        AlertRule("noisy_neighbor", "serving_tenant_kv_share",
                  ">", NOISY_KV_SHARE, kind="noisy_neighbor",
                  severity="warn"),
    ]


class _TenantMetricsView:
    """Synthetic registry view for the tenancy ``AlertEngine``: exposes
    ``serving_goodput_burn{objective=,tenant=}`` and
    ``serving_tenant_kv_share{tenant=}`` keys built from the ledger
    (nothing is *registered*, so the process-global burn family keeps
    its ``{objective=}`` schema — the ``_BurnMetricsView`` idiom), while
    ``counter()`` delegates to the real default registry so
    ``alerts_fired_total`` aggregates normally."""

    def __init__(self, ledger: "CostLedger"):
        self._ledger = ledger

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for tenant, burns in self._ledger.burn().items():
            for objective, burn in burns.items():
                if burn is not None:
                    out[f'serving_goodput_burn{{objective="{objective}",'
                        f'tenant="{tenant}"}}'] = burn
        for tenant, share in self._ledger.kv_share().items():
            out[f'serving_tenant_kv_share{{tenant="{tenant}"}}'] = share
        return out

    def counter(self, *args, **kwargs):
        from elephas_tpu import obs
        return obs.default_registry().counter(*args, **kwargs)


class CostLedger:
    """Per-tenant cost accounting (thread-safe).

    Scalar updates hold one small lock; per-tenant goodput records and
    registry mirrors run outside it (each surface takes its own lock),
    so attribution adds a dict lookup + integer adds to the hot paths.

    Parameters
    ----------
    clock: shared with the engine/scheduler so queue seconds and
        block-second integration replay deterministically under seeded
        fake clocks.
    objectives: the SLO pack each tenant's goodput ledger evaluates;
        defaults to the stock serving pack.
    registry: where the ``serving_tenant_goodput_burn`` mirror lands;
        None → the process default, resolved lazily (the standing
        latch idiom — a failed bind disables the mirror, it never
        takes the serving path down).
    """

    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 objectives: Optional[Sequence[SLOObjective]] = None,
                 registry=None):
        self.clock = clock
        self._objectives = objectives
        self._registry = registry
        self._lock = locksan.make_lock("CostLedger._lock")
        self._tenants: Dict[str, TenantCosts] = {}
        self._goodput: Dict[str, GoodputLedger] = {}
        self._burn_gauge = None   # lazy family; False after failed bind
        self._alerts: Optional[AlertEngine] = None

    # -- row access ---------------------------------------------------------

    @staticmethod
    def resolve(tenant: Optional[str]) -> str:
        """Normalize a request tag: untagged bills ``default``."""
        return tenant if tenant else DEFAULT_TENANT

    def _row(self, tenant: Optional[str]) -> TenantCosts:
        name = self.resolve(tenant)
        row = self._tenants.get(name)
        if row is None:
            row = self._tenants.setdefault(name, TenantCosts())
        return row

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    # -- cost sites ---------------------------------------------------------

    def record_submit(self, tenant: Optional[str]) -> None:
        with self._lock:
            self._row(tenant).submitted += 1

    def record_reject(self, tenant: Optional[str]) -> None:
        with self._lock:
            self._row(tenant).rejected += 1

    def record_requeue(self, tenant: Optional[str]) -> None:
        """A mid-flight death sent this request back through dispatch —
        the tag survived; the hop is itself a billable event."""
        with self._lock:
            self._row(tenant).requeues += 1

    def record_queue(self, tenant: Optional[str], seconds: float) -> None:
        """Admission-queue residency, billed when the request leaves the
        queue (admitted, expired, or rejected-on-pop)."""
        with self._lock:
            self._row(tenant).queue_seconds += max(0.0, seconds)

    def record_prefill(self, tenant: Optional[str], tokens: int,
                       cached: int = 0) -> None:
        """Prompt tokens processed for this tenant; ``cached`` of them
        came from the prefix cache (paid for by whoever filled it —
        the discount is visible, not hidden)."""
        with self._lock:
            row = self._row(tenant)
            row.prefill_tokens += int(tokens)
            row.cached_prefill_tokens += int(cached)

    def record_decode(self, tenant: Optional[str], tokens: int = 1) -> None:
        """Tokens emitted (harvest sites bill incrementally; the sum
        over tenants must equal ``ServingMetrics.tokens_out``)."""
        with self._lock:
            self._row(tenant).decode_tokens += int(tokens)

    def record_spec(self, tenant: Optional[str], *, drafted: int,
                    accepted: int, emitted: int, windows: int = 1) -> None:
        """One tenant's share of a speculative-decode window."""
        with self._lock:
            row = self._row(tenant)
            row.spec_windows += int(windows)
            row.spec_drafted += int(drafted)
            row.spec_accepted += int(accepted)
            row.spec_emitted += int(emitted)

    def record_block_seconds(self, tenant: Optional[str],
                             seconds: float, *, cow: bool = False) -> None:
        """KV block-occupancy integral for one owner slot interval
        (``PagedKVPool`` bills these; a COW fork's fresh block bills the
        forking tenant from the copy instant)."""
        with self._lock:
            row = self._row(tenant)
            row.kv_block_seconds += max(0.0, seconds)
            if cow:
                row.cow_copies += 1

    def record_status(self, tenant: Optional[str], status: str) -> None:
        """Terminal status for one request (scheduler-side — bills ALL
        traffic, canaries included: a canary's tokens and blocks are
        real costs, and conservation vs ``ServingMetrics`` needs them)."""
        with self._lock:
            row = self._row(tenant)
            if status == "completed":
                row.completed += 1
            elif status == "timeout":
                row.timed_out += 1
            else:
                row.rejected += 1

    def record_goodput(self, result, now: Optional[float] = None) -> None:
        """One finished request into its tenant's goodput ledger (the
        engine's publish path drives this CANARY-BLIND, mirroring the
        fleet ledger — probe traffic must not move tenant burn)."""
        tenant = self.resolve(getattr(result, "tenant", None))
        with self._lock:
            ledger = self._goodput.get(tenant)
            if ledger is None:
                # Private registry per tenant ledger: its lazy
                # serving_goodput_burn{objective=} mirror must not
                # collide with the process-global family's schema.
                ledger = self._goodput.setdefault(tenant, GoodputLedger(
                    objectives=self._objectives, clock=self.clock,
                    registry=MetricsRegistry()))
        ledger.record(result, now=now)
        self._mirror_burn(tenant, ledger)

    # -- goodput / burn -----------------------------------------------------

    def _gauge(self):
        if self._burn_gauge is None:
            try:
                reg = self._registry
                if reg is None:
                    from elephas_tpu import obs
                    reg = obs.default_registry()
                self._burn_gauge = reg.gauge(
                    "serving_tenant_goodput_burn",
                    help="per-tenant multi-window SLO burn rate (min of "
                         "fast/slow bad fraction over error budget)",
                    labelnames=("objective", "tenant"),
                )
            except Exception:
                self._burn_gauge = False
        return self._burn_gauge

    def _mirror_burn(self, tenant: str, ledger: GoodputLedger) -> None:
        gauge = self._gauge()
        if not gauge:
            return
        for objective, burn in ledger.burn().items():
            if burn is not None:
                gauge.labels(objective=objective, tenant=tenant).set(burn)

    def burn(self) -> Dict[str, Dict[str, Optional[float]]]:
        """tenant → objective → multi-window burn (None pre-traffic)."""
        with self._lock:
            ledgers = dict(self._goodput)
        return {t: ledger.burn() for t, ledger in sorted(ledgers.items())}

    def goodput_ratio(self) -> Dict[str, Optional[float]]:
        """tenant → worst lifetime objective ratio (fleet_top's roll-up
        number, per tenant)."""
        with self._lock:
            ledgers = dict(self._goodput)
        out: Dict[str, Optional[float]] = {}
        for tenant, ledger in sorted(ledgers.items()):
            defined = [v for v in ledger.goodput(None).values()
                       if v is not None]
            out[tenant] = min(defined) if defined else None
        return out

    def kv_share(self) -> Dict[str, float]:
        """tenant → fraction of total integrated block-seconds — only
        when more than one tenant holds a nonzero share (noisiness
        requires a neighbor)."""
        with self._lock:
            held = {t: row.kv_block_seconds
                    for t, row in self._tenants.items()
                    if row.kv_block_seconds > 0.0}
        if len(held) < 2:
            return {}
        total = sum(held.values())
        return {t: s / total for t, s in sorted(held.items())}

    # -- alerts -------------------------------------------------------------

    def evaluate_alerts(self, now: Optional[float] = None) -> List[Dict]:
        """Run the tenancy alert pack (``tenant_burn_high``,
        ``noisy_neighbor``) against the synthetic metrics view; breaches
        land in the flight recorder like every other alert."""
        if self._alerts is None:
            self._alerts = AlertEngine(registry=_TenantMetricsView(self),
                                       rules=tenant_rules(),
                                       clock=self.clock)
        return self._alerts.evaluate(now)

    def alerts_snapshot(self) -> Dict[str, Any]:
        if self._alerts is None:
            return {"rules": [r.to_dict() for r in tenant_rules()],
                    "active": [], "fired": [], "fired_kinds": []}
        return self._alerts.snapshot()

    # -- read-out -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The opsd ``/tenants`` document."""
        with self._lock:
            rows = {t: row.to_dict()
                    for t, row in sorted(self._tenants.items())}
        burns = self.burn()
        ratios = self.goodput_ratio()
        for tenant, row in rows.items():
            tb = burns.get(tenant, {})
            defined = [b for b in tb.values() if b is not None]
            row["goodput"] = {
                "ratio": ratios.get(tenant),
                "burn": tb,
                "burn_worst": max(defined) if defined else None,
            }
        totals: Dict[str, float] = {}
        for row in rows.values():
            for key in ("submitted", "completed", "timed_out", "rejected",
                        "requeues", "prefill_tokens",
                        "cached_prefill_tokens", "decode_tokens",
                        "queue_seconds", "kv_block_seconds", "cow_copies"):
                totals[key] = totals.get(key, 0) + row[key]
        return {
            "tenants": rows,
            "totals": totals,
            "kv_share": self.kv_share(),
            "alerts": self.alerts_snapshot(),
        }


def merge_tenant_docs(docs: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Union N ``/tenants`` documents tenant-wise (the router's view
    over its replicas; the ``FleetAggregator``'s over its roster).

    Counters sum per tenant across documents; spec accept rate is
    recomputed from the summed counts; goodput keeps the **worst** burn
    and the **min** ratio (summing burn across replicas would be a lie
    the same way summing load scores is). Alert state unions with the
    per-document ``fired`` history concatenated.
    """
    tenants: Dict[str, Dict[str, Any]] = {}
    scalar_keys = ("submitted", "completed", "timed_out", "rejected",
                   "requeues", "prefill_tokens", "cached_prefill_tokens",
                   "decode_tokens", "queue_seconds", "kv_block_seconds",
                   "cow_copies")
    spec_keys = ("windows", "drafted", "accepted", "emitted")
    fired: List[Dict[str, Any]] = []
    active: List[Dict[str, Any]] = []
    for doc in docs:
        for name, row in (doc.get("tenants") or {}).items():
            acc = tenants.get(name)
            if acc is None:
                acc = tenants[name] = {k: 0 for k in scalar_keys}
                acc["spec"] = {k: 0 for k in spec_keys}
                acc["goodput"] = {"ratio": None, "burn_worst": None}
            for k in scalar_keys:
                acc[k] += row.get(k, 0)
            for k in spec_keys:
                acc["spec"][k] += (row.get("spec") or {}).get(k, 0)
            good = row.get("goodput") or {}
            ratio = good.get("ratio")
            if ratio is not None:
                prev = acc["goodput"]["ratio"]
                acc["goodput"]["ratio"] = (ratio if prev is None
                                           else min(prev, ratio))
            burn = good.get("burn_worst")
            if burn is not None:
                prev = acc["goodput"]["burn_worst"]
                acc["goodput"]["burn_worst"] = (burn if prev is None
                                                else max(prev, burn))
        alerts = doc.get("alerts") or {}
        fired.extend(alerts.get("fired") or [])
        active.extend(alerts.get("active") or [])
    for acc in tenants.values():
        drafted = acc["spec"]["drafted"]
        acc["spec"]["accept_rate"] = (acc["spec"]["accepted"] / drafted
                                      if drafted else None)
    totals: Dict[str, float] = {}
    for acc in tenants.values():
        for k in scalar_keys:
            totals[k] = totals.get(k, 0) + acc[k]
    return {
        "tenants": {t: tenants[t] for t in sorted(tenants)},
        "totals": totals,
        "alerts": {"active": active, "fired": fired,
                   "fired_kinds": sorted({a.get("kind") for a in fired
                                          if "kind" in a})},
        "merged_from": len(docs),
    }
