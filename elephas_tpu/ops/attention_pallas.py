"""Pallas TPU flash-attention forward kernel.

Blockwise causal softmax attention with *streamed* K/V: the grid is
(batch*heads, q_tiles, k_tiles) and Pallas pipelines one (block_k,
head_dim) K/V tile at a time through VMEM, so VMEM holds only the current
tiles + the (block_q, head_dim) accumulator regardless of sequence length
— the long-context regime (100k+ tokens) compiles and runs where a
whole-sequence-resident layout would VMEM-OOM. Running row-max/row-sum
live in VMEM scratch, which persists across the innermost (k) grid steps
of a given q tile. Matmuls hit the MXU with f32 accumulation; causal
tiles above the diagonal are skipped via ``pl.when`` (no FLOPs).

Backward pass: fused Pallas kernels (``pallas_flash_attention_bwd``) that
recompute attention weights from the saved (q, k, lse) residuals in the
same streamed-tile structure — dq accumulates over k tiles, dk/dv over q
tiles. The public ``flash_attention`` wrapper (ops/attention.py) wires
forward+backward into a ``jax.custom_vjp``; non-TPU backends fall back to
an XLA blockwise VJP.

Follows /opt/skills/guides/pallas_guide.md (grid/BlockSpec pipelining,
scratch accumulators, 2-D iota, preferred_element_type on MXU matmuls).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")


def _flash_fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    block_q,
    block_k,
    seq_len,
    causal,
    sm_scale,
):
    """Program (b, qi, kj): fold K/V tile kj into q tile qi's accumulator."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: tile kj contributes iff its first key pos <= q tile's last pos.
    needed = jnp.logical_or(
        not causal, kj * block_k <= (qi + 1) * block_q - 1
    )

    @pl.when(needed)
    def _fold():
        q = q_ref[0, ...].astype(jnp.float32) * sm_scale  # (block_q, d)
        k_tile = k_ref[0, ...].astype(jnp.float32)  # (block_k, d)
        v_tile = v_ref[0, ...].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q,
            k_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)

        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_pos < seq_len
        if causal:
            valid = jnp.logical_and(valid, k_pos <= q_pos)
        scores = jnp.where(valid, scores, _NEG_INF)

        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - shift)
        correction = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - shift), 0.0)
        acc_ref[...] = acc_ref[...] * correction + jax.lax.dot_general(
            p,
            v_tile,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new
        l_ref[...] = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)

    @pl.when(kj == num_k - 1)
    def _finalize():
        o_ref[0, ...] = (
            acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
        ).astype(o_ref.dtype)
        # Row log-sum-exp (the backward residual). Fully-masked (padded)
        # rows get a large-negative finite value, so exp(-inf - lse) == 0
        # in the backward kernels instead of NaN.
        m = m_ref[...]
        shift = jnp.where(jnp.isfinite(m), m, 0.0)
        lse = (shift + jnp.log(jnp.maximum(l_ref[...], 1e-30)))[:, 0]
        lse_ref[0, ...] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


def default_blocks(seq_len: int):
    """Measured block tiling on v5-lite (r4 sweep, fwd+bwd causal,
    bh=8, d=64): wide 1024-row q tiles beat 512 by ~1.3x at 2k-4k
    (fewer grid steps amortize the per-tile scratch init/finalize), and
    lose slightly at 8k+ where VMEM pressure bites; 512-wide k tiles
    win everywhere. scripts/attention_bench.py reproduces the table."""
    return (1024 if seq_len <= 4096 else 512), 512


def _sanitize_blocks(seq_len: int, block_q: int, block_k: int):
    """Clamp to the sequence, and keep multi-block tile sizes on the
    TPU-mappable grid (multiples of 128 on the minor-most score dim)."""
    block_q = min(block_q, max(seq_len, 8))
    block_k = min(block_k, max(seq_len, 8))
    if block_q < seq_len:
        block_q = max(128, (block_q // 128) * 128)
    if block_k < seq_len:
        block_k = max(128, (block_k // 128) * 128)
    return block_q, block_k


def _pad_reshape(q, k, v, block_q, block_k):
    batch, heads, seq_len, head_dim = q.shape
    pad_q = (-seq_len) % block_q
    pad_k = (-seq_len) % block_k
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    bh = batch * heads
    qp = qp.reshape(bh, qp.shape[2], head_dim)
    kp = kp.reshape(bh, kp.shape[2], head_dim)
    vp = vp.reshape(bh, vp.shape[2], head_dim)
    return qp, kp, vp


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "return_lse")
)
def pallas_flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    block_q: int = 512,
    block_k: int = 512,
    return_lse: bool = False,
):
    """q, k, v: (batch, heads, seq, head_dim) -> same-shaped output.

    ``return_lse=True`` additionally returns the per-row log-sum-exp
    ``(batch, heads, seq)`` — the residual the Pallas backward needs.
    """
    batch, heads, seq_len, head_dim = q.shape
    sm_scale = 1.0 / (head_dim**0.5)

    block_q, block_k = _sanitize_blocks(seq_len, block_q, block_k)
    qp, kp, vp = _pad_reshape(q, k, v, block_q, block_k)
    bh = batch * heads
    num_q = qp.shape[1] // block_q
    num_k = kp.shape[1] // block_k

    kernel = functools.partial(
        _flash_fwd_kernel,
        block_q=block_q,
        block_k=block_k,
        seq_len=seq_len,
        causal=causal,
        sm_scale=sm_scale,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec(
                (1, block_q, head_dim),
                lambda b, i, j: (b, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, head_dim),
                lambda b, i, j: (b, j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, head_dim),
                lambda b, i, j: (b, j, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, block_q, head_dim),
                lambda b, i, j: (b, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 8, block_q),
                lambda b, i, j: (b, 0, i),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, qp.shape[1], head_dim), q.dtype),
            # lse replicated across 8 sublanes: TPU block tiling wants the
            # second-minor block dim divisible by 8, so a plain (1, block_q)
            # row block is unmappable; the 8x copy is negligible (f32 rows).
            jax.ShapeDtypeStruct((bh, 8, qp.shape[1]), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),  # acc
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max
            pltpu.VMEM((block_q, 1), jnp.float32),  # running sum
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(4 * bh * seq_len * seq_len * head_dim * (0.5 if causal else 1.0)),
            bytes_accessed=int(3 * bh * seq_len * head_dim * q.dtype.itemsize),
            transcendentals=int(bh * seq_len * seq_len),
        ),
    )(qp, kp, vp)
    out = out.reshape(batch, heads, -1, head_dim)[:, :, :seq_len]
    if return_lse:
        return out, lse[:, 0, :].reshape(batch, heads, -1)[:, :, :seq_len]
    return out


# --------------------------------------------------------------- backward


def _flash_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc_ref,
    *, block_q, block_k, seq_len, causal, sm_scale,
):
    """Program (b, qi, kj): fold K/V tile kj into q tile qi's dq.

    dq_i = sm_scale * sum_j p_ij (dO_i.V_j - D_i) k_j, with
    p_ij = exp(sm_scale q_i.k_j - lse_i) and D = rowsum(dO * O)
    (precomputed, streamed in as `delta`). Same streamed-K/V structure as
    the forward: VMEM holds one K/V tile + the (block_q, d) accumulator.
    """
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    needed = jnp.logical_or(not causal, kj * block_k <= (qi + 1) * block_q - 1)

    @pl.when(needed)
    def _fold():
        q = q_ref[0, ...].astype(jnp.float32)
        k_tile = k_ref[0, ...].astype(jnp.float32)
        v_tile = v_ref[0, ...].astype(jnp.float32)
        do = do_ref[0, ...].astype(jnp.float32)
        lse = lse_ref[0, 0, :].astype(jnp.float32)  # (block_q,)
        delta = delta_ref[0, 0, :].astype(jnp.float32)  # (block_q,)

        scores = sm_scale * jax.lax.dot_general(
            q, k_tile, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_pos < seq_len
        if causal:
            valid = jnp.logical_and(valid, k_pos <= q_pos)
        p = jnp.where(valid, jnp.exp(scores - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v_tile, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        dq_acc_ref[...] += jax.lax.dot_general(
            ds, k_tile, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == num_k - 1)
    def _finalize():
        dq_ref[0, ...] = (sm_scale * dq_acc_ref[...]).astype(dq_ref.dtype)


def _flash_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref,
    *, block_q, block_k, seq_len, causal, sm_scale,
):
    """Program (b, kj, qi): fold Q/dO tile qi into k tile kj's dk/dv.

    dv_j = sum_i p_ij dO_i ; dk_j = sm_scale * sum_i p_ij (dO_i.V_j - D_i) q_i.
    Streams Q/dO tiles through VMEM with (block_k, d) accumulators.
    """
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    num_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    # Causal: q tile qi contributes to k tile kj iff its last q pos >= first k pos.
    needed = jnp.logical_or(not causal, (qi + 1) * block_q - 1 >= kj * block_k)

    @pl.when(needed)
    def _fold():
        q = q_ref[0, ...].astype(jnp.float32)
        k_tile = k_ref[0, ...].astype(jnp.float32)
        v_tile = v_ref[0, ...].astype(jnp.float32)
        do = do_ref[0, ...].astype(jnp.float32)
        lse = lse_ref[0, 0, :].astype(jnp.float32)  # (block_q,)
        delta = delta_ref[0, 0, :].astype(jnp.float32)

        # (block_k, block_q): transposed scores, k-major for the accumulators.
        scores_t = sm_scale * jax.lax.dot_general(
            k_tile, q, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 0
        )
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 1
        )
        valid = jnp.logical_and(k_pos < seq_len, q_pos < seq_len)
        if causal:
            valid = jnp.logical_and(valid, k_pos <= q_pos)
        p_t = jnp.where(valid, jnp.exp(scores_t - lse[None, :]), 0.0)
        dv_acc_ref[...] += jax.lax.dot_general(
            p_t, do, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp_t = jax.lax.dot_general(
            v_tile, do, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_k, block_q)
        ds_t = p_t * (dp_t - delta[None, :])
        dk_acc_ref[...] += jax.lax.dot_general(
            ds_t, q, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0, ...] = (sm_scale * dk_acc_ref[...]).astype(dk_ref.dtype)
        dv_ref[0, ...] = dv_acc_ref[...].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k"))
def pallas_flash_attention_bwd(
    q, k, v, o, lse, do, causal: bool = True, block_q: int = 512, block_k: int = 512
):
    """Fused dq/dk/dv with forward recompute of the attention weights from
    (q, k, lse) — the score matrix never materializes in HBM, matching the
    forward's streamed-tile memory profile. Two kernels: dq accumulates
    over k tiles; dk/dv accumulate over q tiles.
    """
    batch, heads, seq_len, head_dim = q.shape
    sm_scale = 1.0 / (head_dim**0.5)
    block_q, block_k = _sanitize_blocks(seq_len, block_q, block_k)

    # D = rowsum(dO * O): tiny elementwise reduction; XLA fuses it.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    qp, kp, vp = _pad_reshape(q, k, v, block_q, block_k)
    dop, _, _ = _pad_reshape(do, k, v, block_q, block_k)
    bh = batch * heads
    padded_q = qp.shape[1]
    pad_rows = padded_q - seq_len
    # 8-sublane replication: see the forward lse out_shape note.
    lsep = jnp.broadcast_to(
        jnp.pad(
            lse.reshape(bh, seq_len).astype(jnp.float32), ((0, 0), (0, pad_rows))
        )[:, None, :],
        (bh, 8, padded_q),
    )
    deltap = jnp.broadcast_to(
        jnp.pad(
            delta.reshape(bh, seq_len).astype(jnp.float32), ((0, 0), (0, pad_rows))
        )[:, None, :],
        (bh, 8, padded_q),
    )
    num_q = padded_q // block_q
    num_k = kp.shape[1] // block_k

    q_spec = pl.BlockSpec(
        (1, block_q, head_dim), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM
    )
    row_spec = pl.BlockSpec(
        (1, 8, block_q), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM
    )
    k_spec = pl.BlockSpec(
        (1, block_k, head_dim), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM
    )

    dq = pl.pallas_call(
        functools.partial(
            _flash_dq_kernel,
            block_q=block_q, block_k=block_k, seq_len=seq_len,
            causal=causal, sm_scale=sm_scale,
        ),
        grid=(bh, num_q, num_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, padded_q, head_dim), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=int(6 * bh * seq_len * seq_len * head_dim * (0.5 if causal else 1.0)),
            bytes_accessed=int(5 * bh * seq_len * head_dim * q.dtype.itemsize),
            transcendentals=int(bh * seq_len * seq_len),
        ),
    )(qp, kp, vp, dop, lsep, deltap)

    # dk/dv: swap the streaming axes — k tiles outer, q tiles inner.
    kq_q_spec = pl.BlockSpec(
        (1, block_q, head_dim), lambda b, j, i: (b, i, 0), memory_space=pltpu.VMEM
    )
    kq_row_spec = pl.BlockSpec(
        (1, 8, block_q), lambda b, j, i: (b, 0, i), memory_space=pltpu.VMEM
    )
    kq_k_spec = pl.BlockSpec(
        (1, block_k, head_dim), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_dkv_kernel,
            block_q=block_q, block_k=block_k, seq_len=seq_len,
            causal=causal, sm_scale=sm_scale,
        ),
        grid=(bh, num_k, num_q),
        in_specs=[kq_q_spec, kq_k_spec, kq_k_spec, kq_q_spec, kq_row_spec, kq_row_spec],
        out_specs=[kq_k_spec, kq_k_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, kp.shape[1], head_dim), k.dtype),
            jax.ShapeDtypeStruct((bh, vp.shape[1], head_dim), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(8 * bh * seq_len * seq_len * head_dim * (0.5 if causal else 1.0)),
            bytes_accessed=int(5 * bh * seq_len * head_dim * q.dtype.itemsize),
            transcendentals=int(bh * seq_len * seq_len),
        ),
    )(qp, kp, vp, dop, lsep, deltap)

    dq = dq.reshape(batch, heads, -1, head_dim)[:, :, :seq_len]
    dk = dk.reshape(batch, heads, -1, head_dim)[:, :, :seq_len]
    dv = dv.reshape(batch, heads, -1, head_dim)[:, :, :seq_len]
    return dq, dk, dv
