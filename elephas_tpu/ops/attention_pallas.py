"""Pallas TPU flash-attention forward kernel.

Blockwise causal softmax attention with *streamed* K/V: the grid is
(batch*heads, q_tiles, k_tiles) and Pallas pipelines one (block_k,
head_dim) K/V tile at a time through VMEM, so VMEM holds only the current
tiles + the (block_q, head_dim) accumulator regardless of sequence length
— the long-context regime (100k+ tokens) compiles and runs where a
whole-sequence-resident layout would VMEM-OOM. Running row-max/row-sum
live in VMEM scratch, which persists across the innermost (k) grid steps
of a given q tile. Matmuls hit the MXU with f32 accumulation; causal
tiles above the diagonal are skipped via ``pl.when`` (no FLOPs).

Backward pass: the public ``flash_attention`` wrapper (ops/attention.py)
wires this forward into a ``jax.custom_vjp`` whose backward re-computes
via the XLA blockwise implementation.

Follows /opt/skills/guides/pallas_guide.md (grid/BlockSpec pipelining,
scratch accumulators, 2-D iota, preferred_element_type on MXU matmuls).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")


def _flash_fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    block_q,
    block_k,
    seq_len,
    causal,
    sm_scale,
):
    """Program (b, qi, kj): fold K/V tile kj into q tile qi's accumulator."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: tile kj contributes iff its first key pos <= q tile's last pos.
    needed = jnp.logical_or(
        not causal, kj * block_k <= (qi + 1) * block_q - 1
    )

    @pl.when(needed)
    def _fold():
        q = q_ref[0, ...].astype(jnp.float32) * sm_scale  # (block_q, d)
        k_tile = k_ref[0, ...].astype(jnp.float32)  # (block_k, d)
        v_tile = v_ref[0, ...].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q,
            k_tile,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)

        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_pos < seq_len
        if causal:
            valid = jnp.logical_and(valid, k_pos <= q_pos)
        scores = jnp.where(valid, scores, _NEG_INF)

        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - shift)
        correction = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - shift), 0.0)
        acc_ref[...] = acc_ref[...] * correction + jax.lax.dot_general(
            p,
            v_tile,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new
        l_ref[...] = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)

    @pl.when(kj == num_k - 1)
    def _finalize():
        o_ref[0, ...] = (
            acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k"))
def pallas_flash_attention(q, k, v, causal: bool = True, block_q: int = 512, block_k: int = 512):
    """q, k, v: (batch, heads, seq, head_dim) -> same-shaped output."""
    batch, heads, seq_len, head_dim = q.shape
    sm_scale = 1.0 / (head_dim**0.5)

    block_q = min(block_q, max(seq_len, 8))
    block_k = min(block_k, max(seq_len, 8))
    pad_q = (-seq_len) % block_q
    pad_k = (-seq_len) % block_k
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))

    bh = batch * heads
    qp = qp.reshape(bh, qp.shape[2], head_dim)
    kp = kp.reshape(bh, kp.shape[2], head_dim)
    vp = vp.reshape(bh, vp.shape[2], head_dim)
    num_q = qp.shape[1] // block_q
    num_k = kp.shape[1] // block_k

    kernel = functools.partial(
        _flash_fwd_kernel,
        block_q=block_q,
        block_k=block_k,
        seq_len=seq_len,
        causal=causal,
        sm_scale=sm_scale,
    )
    out = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec(
                (1, block_q, head_dim),
                lambda b, i, j: (b, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, head_dim),
                lambda b, i, j: (b, j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, head_dim),
                lambda b, i, j: (b, j, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, head_dim),
            lambda b, i, j: (b, i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((bh, qp.shape[1], head_dim), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),  # acc
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max
            pltpu.VMEM((block_q, 1), jnp.float32),  # running sum
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(4 * bh * seq_len * seq_len * head_dim * (0.5 if causal else 1.0)),
            bytes_accessed=int(3 * bh * seq_len * head_dim * q.dtype.itemsize),
            transcendentals=int(bh * seq_len * seq_len),
        ),
    )(qp, kp, vp)
    out = out.reshape(batch, heads, -1, head_dim)
    return out[:, :, :seq_len]
