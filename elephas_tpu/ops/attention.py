"""Blockwise (flash) attention.

``flash_attention(q, k, v, causal)`` computes softmax attention in tiles
so the (seq × seq) score matrix never materializes in HBM. On TPU a
Pallas kernel is used (MXU-tiled, VMEM-resident running max/sum); on CPU
(tests) an XLA ``lax.scan`` blockwise implementation with identical
numerics runs instead.

Shapes: q, k, v are (batch, heads, seq, head_dim); returns the same.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _blockwise_reference(q, k, v, causal: bool, block_q: int, block_k: int):
    """Numerically-stable streaming softmax over k/v blocks (XLA path)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=q.dtype))
    q = q * scale

    nq = -(-sq // block_q)
    nk = -(-sk // block_k)
    # Pad seq dims to block multiples (masked out below).
    q = jnp.pad(q, ((0, 0), (0, 0), (0, nq * block_q - sq), (0, 0)))
    k = jnp.pad(k, ((0, 0), (0, 0), (0, nk * block_k - sk), (0, 0)))
    v = jnp.pad(v, ((0, 0), (0, 0), (0, nk * block_k - sk), (0, 0)))

    q_blocks = q.reshape(b, h, nq, block_q, d)

    def process_q_block(qi, q_blk):
        q_pos = qi * block_q + jnp.arange(block_q)

        def scan_kv(carry, kj):
            acc, row_max, row_sum = carry
            k_blk = jax.lax.dynamic_slice_in_dim(k, kj * block_k, block_k, axis=2)
            v_blk = jax.lax.dynamic_slice_in_dim(v, kj * block_k, block_k, axis=2)
            scores = jnp.einsum("bhqd,bhkd->bhqk", q_blk, k_blk)
            k_pos = kj * block_k + jnp.arange(block_k)
            valid = k_pos[None, :] < sk
            if causal:
                valid = valid & (k_pos[None, :] <= q_pos[:, None])
            scores = jnp.where(valid[None, None], scores, -jnp.inf)
            new_max = jnp.maximum(row_max, scores.max(axis=-1))
            # Renormalize the running accumulator to the new max.
            correction = jnp.exp(row_max - new_max)
            correction = jnp.where(jnp.isfinite(row_max), correction, 0.0)
            weights = jnp.exp(scores - new_max[..., None])
            acc = acc * correction[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", weights, v_blk
            )
            row_sum = row_sum * correction + weights.sum(axis=-1)
            return (acc, new_max, row_sum), None

        acc0 = jnp.zeros((b, h, block_q, d), dtype=q.dtype)
        max0 = jnp.full((b, h, block_q), -jnp.inf, dtype=q.dtype)
        sum0 = jnp.zeros((b, h, block_q), dtype=q.dtype)
        (acc, _, row_sum), _ = jax.lax.scan(
            scan_kv, (acc0, max0, sum0), jnp.arange(nk)
        )
        return acc / jnp.maximum(row_sum[..., None], 1e-30)

    outs = [
        process_q_block(qi, q_blocks[:, :, qi]) for qi in range(nq)
    ]
    out = jnp.concatenate(outs, axis=2)
    return out[:, :, :sq]


def cache_attention_mask(max_len, seq, idx, pad_offset=None):
    """Validity mask for KV-cache incremental attention.

    The current block of ``seq`` queries lands at cache columns
    ``idx + [0, seq)``; each query may attend every cached column up to
    its own (causal within the block, everything cached before it), but
    never the leading left-pad columns of its row.

    ``idx``: scalar () — one shared write position (the ``generate``
    path, where left-padding aligns every row's columns) — or (batch,)
    — per-row positions (the serving KV-pool path, where slots decode at
    independent depths). ``pad_offset``: None, or (batch,) count of
    left-pad columns per row; column ``j`` is a pad key for row ``b``
    iff ``j < pad_offset[b]``.

    Returns a bool mask broadcastable against (batch, heads, seq,
    max_len) scores: (1, 1, seq, max_len) when both idx and pad_offset
    are row-independent, else (batch, 1, seq, max_len).
    """
    cols = jnp.arange(max_len)
    rows = jnp.arange(seq)
    idx = jnp.asarray(idx)
    if idx.ndim == 0:
        # (seq, max_len): same causal frontier for every row.
        valid = cols[None, :] <= idx + rows[:, None]
        valid = valid[None, :, :]  # (1, seq, max_len)
    else:
        # (batch, seq, max_len): per-row frontier.
        valid = cols[None, None, :] <= idx[:, None, None] + rows[None, :, None]
    if pad_offset is not None:
        pad_offset = jnp.asarray(pad_offset)
        valid = valid & (cols[None, None, :] >= pad_offset[:, None, None])
    return valid[:, None]  # broadcast over heads


# -- paged KV-cache gather/scatter ------------------------------------------
#
# The serving pool's paged layout stores K/V as physical blocks
# (num_blocks, heads, block_size, head_dim) shared across slots through a
# (max_slots, blocks_per_slot) block table. These helpers are the bridge
# between that layout and the contiguous (slots, heads, len, head_dim)
# view the dense cache-attention path consumes: gather through the table
# before the apply, scatter exactly the freshly-written columns back
# after it. Unallocated table entries carry the OUT-OF-RANGE id
# ``num_blocks``: gathers clamp (the garbage columns sit at or past
# every reader's cache index, so the causal mask hides them) and
# scatters drop (``mode="drop"``), so no index is ever negative.


def paged_to_contiguous(leaf, table):
    """Gather a paged K/V leaf into per-slot contiguous rows.

    ``leaf``: (num_blocks, heads, block_size, head_dim) physical blocks;
    ``table``: (max_slots, blocks_per_slot) int32 block ids. Returns
    (max_slots, heads, blocks_per_slot * block_size, head_dim).
    """
    slots, bps = table.shape
    _, heads, bs, head_dim = leaf.shape
    gathered = leaf[table]  # (slots, bps, heads, bs, head_dim); OOB clamps
    gathered = jnp.transpose(gathered, (0, 2, 1, 3, 4))
    return gathered.reshape(slots, heads, bps * bs, head_dim)


def slot_row_to_contiguous(leaf, row_table):
    """Gather ONE slot's blocks as a batch-1 contiguous cache row.

    ``row_table``: (blocks_per_slot,) int32 block ids for the slot.
    Returns (1, heads, blocks_per_slot * block_size, head_dim).
    """
    gathered = leaf[row_table]  # (bps, heads, bs, head_dim)
    gathered = jnp.transpose(gathered, (1, 0, 2, 3))
    heads, bps, bs, head_dim = gathered.shape
    return gathered.reshape(heads, bps * bs, head_dim)[None]


def scatter_decode_columns(pool_leaf, contiguous, table, idx, active):
    """Write each slot's just-decoded column back into its physical block.

    ``contiguous`` is the (max_slots, heads, L, head_dim) view AFTER the
    apply wrote column ``idx[s]`` for every slot s (``idx`` is the
    PRE-advance cache index vector). Inactive lanes scatter to the
    out-of-range block id and drop — their computed column is garbage by
    contract.
    """
    num_blocks, _, bs, _ = pool_leaf.shape
    written = jnp.take_along_axis(
        contiguous, idx[:, None, None, None], axis=2
    )[:, :, 0, :]  # (max_slots, heads, head_dim)
    blk = jnp.take_along_axis(table, (idx // bs)[:, None], axis=1)[:, 0]
    target = jnp.where(active, blk, num_blocks)
    return pool_leaf.at[target, :, idx % bs].set(written, mode="drop")


def scatter_prefill_columns(pool_leaf, row_table, start, chunk):
    """Write one prefill chunk's columns ``[start, start + C)`` of ONE
    slot into its physical blocks.

    ``chunk``: (heads, C, head_dim) — the freshly-computed K or V
    columns. Columns landing in unallocated blocks (right-pad garbage
    past the slot's allocation) hit the out-of-range id and drop.
    """
    bs = pool_leaf.shape[2]
    cols = start + jnp.arange(chunk.shape[1])
    target = row_table[cols // bs]
    return pool_leaf.at[target, :, cols % bs].set(
        jnp.transpose(chunk, (1, 0, 2)), mode="drop"
    )


def scatter_spec_columns(pool_leaf, contiguous, table, idx, count, active):
    """Write each slot's ``count`` freshly-computed columns
    ``[idx[s], idx[s] + count)`` back into its physical blocks — the
    multi-column sibling of ``scatter_decode_columns`` for speculative
    draft/verify windows.

    ``contiguous`` is the (max_slots, heads, L, head_dim) view AFTER an
    apply with seq == count wrote those columns (``idx`` is the
    PRE-advance cache index vector; ``count`` is static). Inactive lanes
    and columns past the row's virtual capacity scatter to the
    out-of-range block id and drop. Rejected-suffix columns are written
    too — they sit at or past every reader's causal frontier until a
    later accepted token overwrites them, so they are never attended.
    """
    num_blocks, heads, bs, head_dim = pool_leaf.shape
    slots, bps = table.shape
    cols = idx[:, None] + jnp.arange(count)[None, :]  # (slots, count)
    written = jnp.take_along_axis(
        contiguous, cols[:, None, :, None], axis=2
    )  # (slots, heads, count, head_dim)
    written = jnp.transpose(written, (0, 2, 1, 3)).reshape(
        slots * count, heads, head_dim
    )
    blk = jnp.take_along_axis(
        table, jnp.clip(cols // bs, 0, bps - 1), axis=1
    )  # (slots, count)
    ok = active[:, None] & (cols < bps * bs)
    target = jnp.where(ok, blk, num_blocks)
    return pool_leaf.at[target.reshape(-1), :, (cols % bs).reshape(-1)].set(
        written, mode="drop"
    )


def pallas_min_seq(head_dim: int) -> int:
    """Sequence length above which the Pallas kernels beat the XLA
    blockwise path, as a function of head_dim (VERDICT r4 #7 — the r4
    constant was tuned on head_dim 64 only).

    Measured r5 on the dev chip (`scripts/attention_bench.py --dims 32
    64 128`, 40–80 steps, fwd+bwd): at seq 2048 the two paths are
    within tunnel noise of parity for EVERY measured head_dim (0.74×–
    1.25× across repeated runs); at ≥3072 Pallas wins clearly (1.4×–
    2.3×) and keeps growing (4×–5× at 8192); at ≤1024 XLA wins. The
    crossover therefore sits between 2k and 3k regardless of head_dim
    in [32, 128] — the threshold stays 2048 there (worst case is
    noise-level parity on one marginal shape, and every longer length
    wins). Head dims OUTSIDE the measured range — larger than 128 or
    smaller than 32 — fall back to a conservative 4096 so an unmeasured
    tiling can't silently regress.
    """
    return 2048 if 32 <= head_dim <= 128 else 4096


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _use_pallas(q) -> bool:
    return _on_tpu() and q.shape[2] >= pallas_min_seq(q.shape[3])


def _forward_impl(q, k, v, causal, block_q, block_k):
    if _use_pallas(q):
        from elephas_tpu.ops.attention_pallas import pallas_flash_attention

        return pallas_flash_attention(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k
        )
    return _blockwise_reference(q, k, v, causal, block_q, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    return _forward_impl(q, k, v, causal, block_q, block_k)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    if _use_pallas(q):
        from elephas_tpu.ops.attention_pallas import pallas_flash_attention

        # Save (o, lse) so the backward recomputes attention weights from
        # the streamed tiles — fused Pallas dq/dk/dv, no score matrix.
        o, lse = pallas_flash_attention(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k,
            return_lse=True,
        )
        return o, (q, k, v, o, lse)
    return _blockwise_reference(q, k, v, causal, block_q, block_k), (q, k, v)


def _flash_bwd(causal, block_q, block_k, residuals, g):
    if len(residuals) == 5:  # TPU: fused Pallas backward kernels
        from elephas_tpu.ops.attention_pallas import pallas_flash_attention_bwd

        q, k, v, o, lse = residuals
        return pallas_flash_attention_bwd(
            q, k, v, o, lse, g, causal=causal, block_q=block_q, block_k=block_k
        )
    # Other backends: backward via the XLA blockwise path (same numerics).
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _blockwise_reference(q_, k_, v_, causal, block_q, block_k),
        q,
        k,
        v,
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k"))
def flash_attention(
    q, k, v, causal: bool = True,
    block_q: int | None = None, block_k: int | None = None,
):
    """Blockwise attention with flash memory semantics at every length:
    the custom VJP recomputes attention weights in backward (never
    retaining O(seq^2) residuals), with the KERNEL chosen per shape —
    Pallas on TPU for seq >= ``pallas_min_seq(head_dim)`` where its
    fused backward wins (4-5x at 8k), XLA blockwise below, where Pallas
    launch/tiling overhead loses (scripts/attention_bench.py).

    Block sizes default to the measured per-length tiling
    (``attention_pallas.default_blocks``); pass explicitly to override.
    Differentiable. q/k/v: (batch, heads, seq, head_dim).
    """
    if block_q is None or block_k is None:
        from elephas_tpu.ops.attention_pallas import default_blocks

        dq, dk = default_blocks(q.shape[2])
        block_q = block_q if block_q is not None else dq
        block_k = block_k if block_k is not None else dk
    return _flash(q, k, v, causal, block_q, block_k)
