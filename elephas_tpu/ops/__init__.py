"""Custom TPU ops (Pallas kernels with XLA fallbacks).

The reference has no op layer — TF kernels are L0 borrowing (SURVEY.md
§1). Here the hot ops the compiler can't already fuse optimally get
hand-written Pallas kernels, with pure-XLA fallbacks for CPU tests.
"""
