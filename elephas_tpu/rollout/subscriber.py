"""Per-engine weight delivery: version-gated PS pulls at step boundaries.

A ``WeightSubscriber`` is the only thing that ever swaps a serving
engine's weights. The engine calls ``on_step(engine)`` from
``_on_step_boundary`` — under the step lock, after the scheduler step —
so every install is atomic with respect to dispatch: no compiled program
is in flight, and a speculative draft+verify window (one scheduler step)
can never straddle a swap.

Three modes, cheapest steady state first:

- **hold** (managed default): no wire traffic at all. The
  ``RolloutController`` moves the pin; an unpinned managed engine serves
  what it has.
- **pinned**: one pinned pull (live buffer or WAL history) when the
  engine is not yet at the pin, then zero traffic until the pin moves.
  ``VersionUnavailable`` is definitive — the subscriber stops retrying
  that pin (``pin_failed``) and the controller falls back to a peer
  copy via ``offer``.
- **follow** (standalone, ``follow=True``): poll the live version every
  ``every`` steps. Steady state costs K not-modified frames per poll
  (the wire layer's version gate); a version change costs one full
  transfer and one ``weight_swap``.

Failures degrade, never stall: any pull error counts ``failures``,
notes ``weight_pull_fail``, and the engine keeps serving its current
weights — delivery is not a liveness dependency.

A spec-decoding engine's ``DraftModelSource`` (``subscribed=True``)
rides the same cadence: after each successful target poll the
subscriber calls ``draft.refresh()``, so the draft model costs no extra
polling schedule of its own.
"""

from __future__ import annotations

from typing import Optional

from elephas_tpu import obs
from elephas_tpu.parameter.client import VersionUnavailable
from elephas_tpu.utils import locksan

__all__ = ["WeightSubscriber"]


class WeightSubscriber:
    """See module docstring. One subscriber per engine — the step/pin
    state is per-engine, and sharing one across engines would alias
    their cadences. The wire ``client`` CAN be shared (its fan-out and
    pull cache are thread-safe, and pinned steady state is silent)."""

    def __init__(self, client, every: int = 1, follow: bool = False,
                 draft_source=None):
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        self.client = client
        self.every = int(every)
        self.follow = bool(follow)
        self.draft = draft_source
        # pin/offer state crosses threads: the controller writes from
        # its tick thread, on_step reads from the serve thread.
        self._lock = locksan.make_lock("WeightSubscriber._lock")
        self._pinned: Optional[int] = None
        self._pin_failed = False
        self._offered = None  # (tree, version) staged for next boundary
        self._steps = 0
        self.pulls = 0      # network polls that completed
        self.unchanged = 0  # polls answered entirely by not-modified
        self.swaps = 0      # installs (version actually changed)
        self.failures = 0   # failed pulls (engine kept serving)

    # -- control plane (any thread) -----------------------------------------

    def attach(self, engine) -> "WeightSubscriber":
        """Register on ``engine`` (its step-boundary hook calls us) and
        adopt its spec draft source when that source opted into
        subscription — one cadence for target AND draft."""
        engine.subscriber = self
        spec = getattr(engine, "spec", None)
        source = getattr(spec, "source", None)
        if self.draft is None and getattr(source, "subscribed", False):
            self.draft = source
        return self

    def pin(self, version: int) -> None:
        """Target one exact version; the next step boundary pulls it
        (pinned read), then the subscriber goes silent until the pin
        moves."""
        with self._lock:
            self._pinned = int(version)
            self._pin_failed = False

    def unpin(self) -> None:
        with self._lock:
            self._pinned = None
            self._pin_failed = False

    @property
    def pinned(self) -> Optional[int]:
        with self._lock:
            return self._pinned

    @property
    def pin_failed(self) -> bool:
        """True when the current pin came back ``VersionUnavailable`` —
        a definitive answer; the controller must supply the bytes
        another way (``offer``) or move the pin."""
        with self._lock:
            return self._pin_failed

    def offer(self, tree, version: Optional[int]) -> None:
        """Stage a host tree for installation at the next step boundary
        — the controller's peer-copy rollback path when the WAL has
        pruned the pinned version. Atomicity is unchanged: the install
        still happens under the step lock."""
        with self._lock:
            self._offered = (tree, version)

    def nudge(self, engine) -> bool:
        """Give an idle engine a synthetic step boundary. Pins and
        offers normally land at the next decode-step boundary — but an
        engine with no traffic has none, and a promotion wave must not
        depend on traffic for liveness. Taking the engine's step lock
        non-blocking preserves the atomicity contract exactly: the lock
        free means no compiled program is in flight (same invariant as
        the real boundary hook), and the lock busy means the engine is
        mid-step and will run the hook itself moments later. Returns
        whether the boundary ran. Engines without a step lock (bare
        fakes) are never nudged — they must step explicitly."""
        lock = getattr(engine, "_step_lock", None)
        if lock is None or not lock.acquire(blocking=False):
            return False
        try:
            self.on_step(engine)
        finally:
            lock.release()
        return True

    def snapshot(self) -> dict:
        with self._lock:
            pinned, pin_failed = self._pinned, self._pin_failed
        return {
            "pinned": pinned, "pin_failed": pin_failed,
            "follow": self.follow, "every": self.every,
            "steps": self._steps, "pulls": self.pulls,
            "unchanged": self.unchanged, "swaps": self.swaps,
            "failures": self.failures,
            "draft_shared": self.draft is not None,
        }

    # -- data plane (serve thread, under the engine's step lock) ------------

    def on_step(self, engine) -> None:
        """One step-boundary tick. Cheap in every steady state: staged
        offer → install it; pinned and already there → return; hold
        mode → return; follow mode → poll on the ``every`` cadence."""
        self._steps += 1
        with self._lock:
            offered, self._offered = self._offered, None
            pinned, pin_failed = self._pinned, self._pin_failed
        if offered is not None:
            self._install(engine, offered[0], offered[1])
            return
        if pinned is not None:
            if pin_failed or engine.model_version == pinned:
                return
            self._pull_pinned(engine, pinned)
            return
        if not self.follow:
            return
        if (self._steps - 1) % self.every != 0:
            return
        self._pull_live(engine)

    def _pull_live(self, engine) -> None:
        try:
            version, tree = self.client.pull()
        except Exception as err:
            self._note_fail(engine, err)
            return
        self.pulls += 1
        if version == engine.model_version and version is not None:
            self.unchanged += 1
        elif version is None and engine.model_version is None:
            # A versionless server: deliver once, then treat every
            # identical answer as unchanged rather than re-swapping.
            self._install(engine, tree, None)
        else:
            self._install(engine, tree, version)
        self._refresh_draft()

    def _pull_pinned(self, engine, pinned: int) -> None:
        try:
            version, tree = self.client.pull(version=pinned)
        except VersionUnavailable as err:
            with self._lock:
                if self._pinned == pinned:
                    self._pin_failed = True  # definitive: stop retrying
            self._note_fail(engine, err, pinned=pinned)
            return
        except Exception as err:
            self._note_fail(engine, err, pinned=pinned)  # retried next step
            return
        self.pulls += 1
        self._install(engine, tree, version)
        self._refresh_draft()

    def _install(self, engine, tree, version: Optional[int]) -> None:
        prior = engine.model_version
        engine.install_weights(tree, version)
        self.swaps += 1
        obs.default_flight_recorder().note(
            "weight_swap", "info", version=version, prior=prior,
            step=self._steps,
        )

    def _refresh_draft(self) -> None:
        if self.draft is None:
            return
        try:
            self.draft.refresh()
        except Exception as err:
            self.failures += 1
            obs.default_flight_recorder().note(
                "weight_pull_fail", "warn", model="draft",
                error=repr(err),
            )

    def _note_fail(self, engine, err,
                   pinned: Optional[int] = None) -> None:
        self.failures += 1
        obs.default_flight_recorder().note(
            "weight_pull_fail", "warn", error=repr(err),
            serving_version=engine.model_version, pinned=pinned,
        )
