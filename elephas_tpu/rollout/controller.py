"""Goodput-gated canary rollout over a serving ``ReplicaSet``.

The controller owns fleet-wide delivery POLICY; the per-engine
``WeightSubscriber`` owns the mechanism. Every managed replica's
subscriber starts in **hold** (no wire traffic); the controller moves
pins, so no replica ever adopts a training push the canary arc has not
judged — "no non-canary replica serves an unapproved version" holds by
construction, not by timing.

The arc, one ``tick()`` at a time (explicit actuation, injectable
clock — the same observe→decide→act shape as ``Router.tick``):

- **idle** — probe the PS group (version-gated: steady state costs K
  not-modified frames). A version that is neither approved nor
  previously rejected starts a canary: ONE replica (first tier in
  ``tier_order`` — prefill before decode) is pinned to the candidate
  and swaps in place, no restart.
- **canary** — bake for ``bake_s`` AND at least ``min_results``
  finished canary requests, then ask the judge. The default
  (``goodput_judge``) compares the canary's worst bake-window goodput
  objective against the rest of the fleet's; the judge is injectable
  (a quality probe comparing canary output against reference tokens is
  the natural production upgrade).
- **promoting** — good verdict: the pin ripples tier-aware, one tier
  per wave (``tier_order``), each wave waiting until its replicas
  report the candidate version before the next tier moves. When the
  whole fleet converges, the candidate becomes the approved version.
- **rollback** — bad verdict: the canary is re-pinned to the approved
  prior version (a pinned WAL read — immune to ongoing training
  pushes). If the WAL has pruned it (``pin_failed``), the controller
  stages a peer copy of a healthy replica's live params (``offer``) —
  rollback never depends on the PS retaining history.

Every transition appends a time-independent event ``{seq, kind,
version, replica, tier}``; their canonical-JSON sha256 is the **rollout
digest** — replay-stable under a fake clock, the post-mortem anchor.
Promotions/rollbacks also land on the incident timeline as
``rollout_promote`` / ``rollout_rollback`` flight notes, and the
``fleet_rollout_age_s`` / ``fleet_version_skew`` gauges feed the
``rollout_stuck`` / ``version_skew`` alert rules (skew is measured over
NON-canary replicas — a long bake is not an incident).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from elephas_tpu import obs
from elephas_tpu.rollout.subscriber import WeightSubscriber
from elephas_tpu.serving.fleet.replica import SERVING
from elephas_tpu.utils import locksan

__all__ = ["RolloutController", "goodput_judge"]


def goodput_judge(tolerance: float = 0.10):
    """Default verdict: the canary's worst bake-window goodput objective
    must sit within ``tolerance`` of the fleet's worst (or near-perfect
    when the fleet has no window evidence). Returns a judge callable
    ``(canary, fleet, window_s, now) -> Optional[bool]`` — ``None``
    means "not enough evidence, keep baking"."""

    def judge(canary, fleet, window_s: float, now: float):
        cvals = [v for v in
                 canary.engine.slo.goodput(window_s, now=now).values()
                 if v is not None]
        if not cvals:
            return None
        c = min(cvals)
        fvals = []
        for rep in fleet:
            if rep.engine is None:
                continue
            vals = [v for v in
                    rep.engine.slo.goodput(window_s, now=now).values()
                    if v is not None]
            if vals:
                fvals.append(min(vals))
        if not fvals:
            return c >= 1.0 - tolerance
        return c >= min(fvals) - tolerance

    return judge


class RolloutController:
    """See module docstring.

    Parameters
    ----------
    replicas: the ``ReplicaSet`` to manage (subscribers are attached to
        every serving replica lazily, and re-attached after restarts —
        a respawned engine comes back pinned to the approved version).
    client: a ``ShardedParameterClient``-shaped client (``pull`` /
        ``pull(version=)``) — the controller's own probe AND, by
        default, the subscribers' shared wire client.
    bake_s / min_results: the bake window — both must be satisfied
        before the judge runs.
    judge: verdict callable (see ``goodput_judge``); injectable.
    tier_order: canary placement and promotion ripple order.
    """

    PHASES = ("idle", "canary", "promoting", "rollback")

    def __init__(self, replicas, client, *, bake_s: float = 2.0,
                 min_results: int = 4,
                 judge: Optional[Callable] = None,
                 tier_order=("prefill", "mono", "decode"),
                 subscriber_every: int = 1,
                 clock=time.monotonic,
                 client_factory: Optional[Callable[[], Any]] = None):
        self.replicas = replicas
        self.client = client
        self.bake_s = float(bake_s)
        self.min_results = int(min_results)
        self.judge = judge if judge is not None else goodput_judge()
        self.tier_order = tuple(tier_order)
        self.subscriber_every = int(subscriber_every)
        self.clock = clock
        self._client_factory = client_factory
        self._subs: Dict[str, WeightSubscriber] = {}
        self._lock = locksan.make_lock("RolloutController._lock")
        self._phase = "idle"
        self._phase_start: Optional[float] = None
        self._seeded = False  # baseline adopted (see _tick_idle)
        self._baseline: Optional[int] = None
        self._approved: Optional[int] = None
        self._candidate: Optional[int] = None
        self._canary_rid: Optional[str] = None
        self._canary_eval0 = 0
        self._promote_tiers: List[str] = []
        self._promote_wave: List[str] = []
        self._rejected: set = set()
        self._events: List[Dict[str, Any]] = []
        self._seq = 0
        self.rollouts = 0
        self.rollbacks = 0
        self.probe_failures = 0
        self._ticker: Optional[threading.Thread] = None
        self._ticker_stop = threading.Event()
        reg = obs.default_registry()
        self._g_age = reg.gauge(
            "fleet_rollout_age_s",
            help="seconds the rollout state machine has sat in its "
                 "current non-idle phase (0 when idle)")
        self._g_skew = reg.gauge(
            "fleet_version_skew",
            help="max minus min served model_version across non-canary "
                 "serving replicas (0 with fewer than two versions)")

    # -- events / digest ----------------------------------------------------

    def _event(self, kind: str, **fields) -> None:
        with self._lock:
            self._seq += 1
            self._events.append({"seq": self._seq, "kind": kind,
                                 **fields})

    def digest(self) -> str:
        """Replay-stable rollout digest: canonical JSON over the
        time-independent event list (events carry seq/kind/version/
        replica/tier, never timestamps — wall time lives in the flight
        notes, which the incident timeline already clock-aligns)."""
        with self._lock:
            blob = json.dumps(self._events, sort_keys=True,
                              separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    # -- subscriber management ----------------------------------------------

    def _make_client(self):
        if self._client_factory is not None:
            return self._client_factory()
        return self.client

    def subscriber_of(self, replica_id: str) -> Optional[WeightSubscriber]:
        return self._subs.get(replica_id)

    def _manage(self) -> None:
        """Attach a held subscriber to every serving engine that lacks
        one (first sight AND post-restart respawns — a fresh engine's
        ``subscriber`` is None). A replica joining an already-delivered
        fleet is pinned straight to the approved version."""
        with self._lock:
            approved = self._approved
        for rep in self.replicas.serving():
            engine = rep.engine
            if engine is None or engine.subscriber is not None:
                continue
            sub = WeightSubscriber(
                self._make_client(), every=self.subscriber_every,
                follow=False,
            ).attach(engine)
            self._subs[rep.replica_id] = sub
            if approved is not None:
                sub.pin(approved)
                sub.nudge(engine)  # a respawn may see no traffic yet

    # -- tick ---------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> str:
        """One observe→decide→act pass; returns the (possibly new)
        phase. Explicit actuation: benches/tests drive it directly on a
        fake clock; ``start_ticker`` wraps it for production."""
        now = self.clock() if now is None else float(now)
        self._manage()
        with self._lock:
            phase = self._phase
        if phase == "idle":
            self._tick_idle(now)
        elif phase == "canary":
            self._tick_canary(now)
        elif phase == "promoting":
            self._tick_promoting(now)
        elif phase == "rollback":
            self._tick_rollback(now)
        self._refresh_gauges(now)
        with self._lock:
            return self._phase

    def _tick_idle(self, now: float) -> None:
        try:
            version, _ = self.client.pull()
        except Exception:
            # A PS outage stalls DELIVERY, never serving: the fleet
            # keeps answering on its current weights.
            self.probe_failures += 1
            return
        with self._lock:
            approved = self._approved
            seeded = self._seeded
            rejected = version in self._rejected
        if version is None:
            return
        if not seeded:
            # First contact: adopt the PS's current version as the
            # approved baseline WITHOUT a canary arc — the fleet is
            # already serving these weights by construction (engines
            # boot from the same params the PS group was built over),
            # so "delivering" them would be a no-op arc that races the
            # first real push.
            with self._lock:
                self._approved = int(version)
                self._baseline = int(version)
                self._seeded = True
            self._event("baseline", version=int(version))
            return
        if version == approved or rejected:
            return
        serving = self.replicas.serving()
        canary = None
        for tier in self.tier_order:
            tiered = [r for r in serving if r.tier == tier]
            if tiered:
                canary = tiered[0]
                break
        if canary is None or canary.engine is None:
            return
        sub = self._subs.get(canary.replica_id)
        if sub is None:
            return
        self._canary_eval0 = canary.engine.slo.snapshot(
            now=now)["evaluated"]
        with self._lock:
            self._candidate = int(version)
            self._canary_rid = canary.replica_id
            self._phase = "canary"
            self._phase_start = now
        canary.rollout_canary = True
        sub.pin(int(version))
        self._event("canary_start", version=int(version),
                    replica=canary.replica_id, tier=canary.tier)

    def _canary(self):
        with self._lock:
            rid = self._canary_rid
        rep = self.replicas.replicas.get(rid) if rid is not None else None
        if rep is None or rep.state != SERVING or rep.engine is None:
            return None
        return rep

    def _abort(self, kind: str) -> None:
        with self._lock:
            version, rid = self._candidate, self._canary_rid
            self._phase = "idle"
            self._phase_start = None
            self._candidate = None
            self._canary_rid = None
        self._clear_canary_flag(rid)
        self._event(kind, version=version, replica=rid)

    def _clear_canary_flag(self, rid: Optional[str]) -> None:
        rep = self.replicas.replicas.get(rid) if rid is not None else None
        if rep is not None:
            rep.rollout_canary = False

    def _tick_canary(self, now: float) -> None:
        canary = self._canary()
        if canary is None:
            self._abort("canary_lost")  # died mid-bake; next tick re-arms
            return
        with self._lock:
            candidate, start = self._candidate, self._phase_start
        sub = self._subs.get(canary.replica_id)
        if canary.engine.model_version != candidate:
            if sub is not None and sub.pin_failed:
                # The trainer outran the WAL window before the canary
                # ever swapped — this candidate is unservable, not bad.
                self._abort("canary_abandoned")
                return
            if sub is not None:
                sub.nudge(canary.engine)  # idle canary: deliver now
            if canary.engine.model_version != candidate:
                return  # still delivering (or degrading on failures)
        if now - start < self.bake_s:
            return
        evaluated = canary.engine.slo.snapshot(now=now)["evaluated"] \
            - self._canary_eval0
        if evaluated < self.min_results:
            return
        fleet = [r for r in self.replicas.serving()
                 if r.replica_id != canary.replica_id]
        verdict = self.judge(canary, fleet, max(now - start, 1e-9), now)
        if verdict is None:
            return
        if verdict:
            self._begin_promote(now)
        else:
            self._begin_rollback(now)

    # -- promotion ----------------------------------------------------------

    def _begin_promote(self, now: float) -> None:
        with self._lock:
            candidate, rid = self._candidate, self._canary_rid
            self._phase = "promoting"
            self._phase_start = now
            self._promote_tiers = list(self.tier_order)
            self._promote_wave = []
        self._event("promote_start", version=candidate, replica=rid)
        self._advance_promote(now)

    def _advance_promote(self, now: float) -> None:
        with self._lock:
            candidate = self._candidate
            wave = list(self._promote_wave)
        for rid in wave:
            rep = self.replicas.replicas.get(rid)
            if rep is None or rep.state != SERVING or rep.engine is None:
                continue  # left the roster; don't wedge the ripple
            if rep.engine.model_version != candidate:
                # Delivery must not depend on traffic: an idle engine
                # has no step boundaries, so hand it a synthetic one.
                sub = self._subs.get(rid)
                if sub is not None and not sub.pin_failed:
                    sub.nudge(rep.engine)
                if rep.engine.model_version != candidate:
                    return  # wave still converging
        while True:
            with self._lock:
                if not self._promote_tiers:
                    break
                tier = self._promote_tiers[0]
            todo = [r for r in self.replicas.serving(tier)
                    if r.engine is not None
                    and r.engine.model_version != candidate
                    and r.replica_id in self._subs]
            if todo:
                for rep in todo:
                    self._subs[rep.replica_id].pin(candidate)
                    self._event("pin", version=candidate,
                                replica=rep.replica_id, tier=rep.tier)
                with self._lock:
                    self._promote_wave = [r.replica_id for r in todo]
                return
            with self._lock:
                self._promote_tiers.pop(0)
        with self._lock:
            self._approved = candidate
            self._phase = "idle"
            self._phase_start = None
            self._candidate = None
            rid = self._canary_rid
            self._canary_rid = None
        self._clear_canary_flag(rid)
        self.rollouts += 1
        self._event("promoted", version=candidate)
        obs.default_flight_recorder().note(
            "rollout_promote", "info", version=candidate,
            replicas=len(self.replicas.serving()),
        )

    def _tick_promoting(self, now: float) -> None:
        self._advance_promote(now)

    # -- rollback -----------------------------------------------------------

    def _begin_rollback(self, now: float) -> None:
        with self._lock:
            candidate, rid = self._candidate, self._canary_rid
            approved = self._approved
            self._rejected.add(candidate)
            self._phase = "rollback"
            self._phase_start = now
        self._event("rollback_start", version=candidate, to=approved,
                    replica=rid)
        obs.default_flight_recorder().note(
            "rollout_rollback", "error", version=candidate,
            to=approved, replica=rid,
        )
        sub = self._subs.get(rid)
        if sub is None:
            return
        if approved is not None:
            sub.pin(approved)  # pinned WAL read — push-race-immune
        else:
            # No PS-delivered prior: restore the pre-delivery weights
            # from a healthy peer (they still serve them).
            sub.unpin()
            peer = self._rollback_peer(rid, None)
            if peer is not None:
                sub.offer(peer.engine.params, None)

    def _rollback_peer(self, canary_rid: str, version: Optional[int]):
        """A healthy replica serving the ``version`` content. A replica
        that was never delivered to (``model_version is None``) serves
        the baseline content by construction, so it counts when the
        approved version IS the seeded baseline."""
        with self._lock:
            baseline = self._baseline
        for rep in self.replicas.serving():
            if rep.replica_id == canary_rid or rep.engine is None:
                continue
            served = rep.engine.model_version
            if served == version or (
                    served is None and version == baseline):
                return rep
        return None

    def _tick_rollback(self, now: float) -> None:
        canary = self._canary()
        if canary is None:
            self._abort("canary_lost")
            return
        with self._lock:
            approved, rid = self._approved, self._canary_rid
        if canary.engine.model_version == approved:
            with self._lock:
                self._phase = "idle"
                self._phase_start = None
                self._candidate = None
                self._canary_rid = None
            self._clear_canary_flag(rid)
            self.rollbacks += 1
            self._event("rolled_back", version=approved, replica=rid)
            return
        sub = self._subs.get(rid)
        if sub is not None and sub.pin_failed and approved is not None:
            # WAL pruned the prior version mid-arc: peer-copy fallback.
            peer = self._rollback_peer(rid, approved)
            if peer is not None:
                sub.offer(peer.engine.params, approved)
                self._event("rollback_peer_copy", version=approved,
                            replica=rid)
        if sub is not None:
            sub.nudge(canary.engine)  # idle canary: roll back now

    # -- observability ------------------------------------------------------

    def _skew(self) -> int:
        with self._lock:
            rid = self._canary_rid
        versions = [r.engine.model_version for r in self.replicas.serving()
                    if r.replica_id != rid and r.engine is not None
                    and r.engine.model_version is not None]
        if len(versions) < 2:
            return 0
        return int(max(versions) - min(versions))

    def _refresh_gauges(self, now: float) -> None:
        with self._lock:
            phase, start = self._phase, self._phase_start
        age = 0.0 if phase == "idle" or start is None \
            else max(0.0, now - start)
        self._g_age.set(age)
        self._g_skew.set(float(self._skew()))

    def doc(self) -> Dict[str, Any]:
        """The opsd ``/rollout`` document (federated by the fleet
        aggregator; rendered by fleet_top's ROLLOUT board)."""
        now = self.clock()
        with self._lock:
            phase, start = self._phase, self._phase_start
            approved, candidate = self._approved, self._candidate
            rid = self._canary_rid
            events = list(self._events[-100:])
        versions = {}
        for rep_id, rep in self.replicas.replicas.items():
            versions[rep_id] = (rep.engine.model_version
                                if rep.engine is not None else None)
        return {
            "active": True,
            "phase": phase,
            "age_s": (0.0 if phase == "idle" or start is None
                      else max(0.0, now - start)),
            "approved_version": approved,
            "candidate_version": candidate,
            "canary": rid,
            "versions": versions,
            "skew": self._skew(),
            "rollouts": self.rollouts,
            "rollbacks": self.rollbacks,
            "probe_failures": self.probe_failures,
            "subscribers": {rep_id: sub.snapshot()
                            for rep_id, sub in self._subs.items()},
            "events": events,
            "digest": self.digest(),
        }

    # -- background ticker ---------------------------------------------------

    def start_ticker(self, interval: float = 0.2,
                     sleep=time.sleep) -> None:
        if self._ticker is not None:
            return
        self._ticker_stop.clear()

        def run():
            while not self._ticker_stop.is_set():
                try:
                    self.tick()
                except Exception:
                    pass  # policy must outlive one bad pass
                sleep(interval)

        self._ticker = threading.Thread(
            target=run, name="rollout-ticker", daemon=True)
        self._ticker.start()

    def stop_ticker(self) -> None:
        if self._ticker is None:
            return
        self._ticker_stop.set()
        self._ticker.join(timeout=5)
        self._ticker = None
