"""Live model delivery: PS-subscribed serving weights with goodput-gated
canary promotion.

Three layers close the training→serving loop:

- ``WeightSubscriber`` (``subscriber.py``) — per-engine delivery: pulls
  TARGET weights version-gated from a ``ShardedParameterClient`` at
  decode-step boundaries (the engine's ``_on_step_boundary`` hook runs
  it under the step lock, so a swap can never land mid-speculative-
  verify), carries the PS version as ``engine.model_version``, and
  degrades to serving the current weights on any pull failure — weight
  delivery is never a liveness dependency.
- ``RolloutController`` (``controller.py``) — fleet policy: new PS
  versions reach ONE canary replica first (in-place swap, no restart),
  the goodput/burn ledgers judge canary-vs-fleet over a bake window,
  and the verdict either ripples the pin tier-aware through the rest of
  the fleet (prefill before decode) or rolls the canary back to the
  pinned prior version. Every transition is an event in a replay-stable
  digest and a flight note on the incident timeline.
- The version-pinning plane lives with the PS itself
  (``parameter.client.get_parameters_pinned`` /
  ``ShardedParameterClient.pull(version=)``, served from the live
  buffer or the WAL history) so rollback and A/B reads never race
  ongoing training pushes.
"""

from elephas_tpu.rollout.controller import RolloutController, goodput_judge
from elephas_tpu.rollout.subscriber import WeightSubscriber

__all__ = [
    "RolloutController",
    "WeightSubscriber",
    "goodput_judge",
]
