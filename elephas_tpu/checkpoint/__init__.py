"""Checkpoint / resume (SURVEY.md §5.4).

The reference only saves the final Keras HDF5 after ``fit``
(``SparkModel.save``) — no mid-training checkpointing, no optimizer
state. The rebuild keeps that API (in ``api.spark_model``) and adds the
one thing TPU users actually need (SURVEY.md §5.3): periodic
``{params, opt_state, batch_stats, step}`` snapshots via Orbax so a
restarted job resumes — Spark's task-retry safety net does not exist on
TPU pods, so this is the honest replacement for the reference's
delegation to Spark fault tolerance.
"""

from elephas_tpu.checkpoint.checkpoint import (  # noqa: F401
    CheckpointManager,
    NoCheckpointError,
    latest_step,
    restore_train_state,
    save_train_state,
)
