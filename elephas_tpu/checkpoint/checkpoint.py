"""Orbax-backed train-state checkpointing.

Layout: ``<directory>/<step>/`` per snapshot (Orbax CheckpointManager
with rotation). Multi-host: Orbax coordinates per-process writes itself;
callers only need every process to call save/restore collectively.
"""

from __future__ import annotations

import os
from typing import Optional

import orbax.checkpoint as ocp

from elephas_tpu.engine.state import TrainState


class NoCheckpointError(FileNotFoundError):
    """No restorable checkpoint exists under the given directory.

    Raised by every restore path here (and by the PS warm-restart WAL,
    ``resilience.wal.SnapshotWAL.restore_latest``) instead of an Orbax
    traceback or a raw ``FileNotFoundError``, so "cold start" is one
    clearly-named branch for callers:

        try:
            state = manager.restore(target)
        except NoCheckpointError:
            state = cold_init()

    Subclasses ``FileNotFoundError`` so pre-existing handlers keep
    working.
    """


def latest_step(directory: str) -> Optional[int]:
    """Highest numbered snapshot step under ``directory``, or None.

    Module-level (no manager construction, no Orbax handshake): a
    filename scan of the ``<directory>/<step>/`` layout both the
    rotating manager and the one-shot savers write. Use it to decide
    cheaply whether a resume is possible before building anything."""
    directory = os.path.abspath(directory)
    try:
        steps = [int(d) for d in os.listdir(directory) if d.isdigit()]
    except (FileNotFoundError, NotADirectoryError):
        return None
    return max(steps) if steps else None


class CheckpointManager:
    """Rotating snapshot manager + fit-callback factory.

    ``host0_only``: restrict Orbax to process 0 (non-collective saves).
    REQUIRED for multi-host async/hogwild fits, where epoch barriers are
    host-local and only host 0 fires callbacks — a default (collective)
    save from host 0 alone would block forever at Orbax's global sync
    waiting for peers that never call save. Sync-mode multi-host fits
    fire callbacks on every host and should keep the collective default.
    """

    def __init__(self, directory: str, keep: int = 3, save_every_epochs: int = 1,
                 host0_only: bool = False):
        import jax

        self.directory = os.path.abspath(directory)
        self.save_every = max(1, save_every_epochs)
        self.host0_only = host0_only
        extra = {}
        if host0_only and jax.process_count() > 1:
            extra["multiprocessing_options"] = ocp.options.MultiprocessingOptions(
                primary_host=0, active_processes={0}
            )
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, **extra
            ),
        )

    def save(self, state: TrainState, step: Optional[int] = None, block: bool = False) -> None:
        """Snapshot ``state``. Async by default — Orbax copies device
        buffers and persists in the background so training never stalls on
        disk; call ``wait()`` (or pass ``block=True``) to barrier."""
        step = int(state.step) if step is None else int(step)
        self._manager.save(step, args=ocp.args.StandardSave(state))
        if block:
            self._manager.wait_until_finished()

    def wait(self) -> None:
        """Barrier on all in-flight saves (call at fit end)."""
        self._manager.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        self._manager.wait_until_finished()
        return self._manager.latest_step()

    def restore(self, target: TrainState, step: Optional[int] = None) -> TrainState:
        step = self.latest_step() if step is None else step
        if step is None:
            raise NoCheckpointError(
                f"no checkpoints under {self.directory} (cold start: "
                "initialize fresh state instead of restoring)"
            )
        return self._manager.restore(step, args=ocp.args.StandardRestore(target))

    def callback(self):
        """An ``(epoch, state, metrics)`` callback for trainer ``fit``.

        Saves are asynchronous on the training path; ``SparkModel.fit``
        barriers via the callback's ``on_fit_end`` hook when training
        completes (standalone trainer users call ``wait()`` themselves).
        """
        return _CheckpointCallback(self)

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()


class _CheckpointCallback:
    def __init__(self, manager: "CheckpointManager"):
        self._manager = manager

    def __call__(self, epoch: int, state: TrainState, metrics: dict) -> None:
        if (epoch + 1) % self._manager.save_every == 0:
            self._manager.save(state)

    def on_fit_end(self) -> None:
        self._manager.wait()


def save_train_state(directory: str, state: TrainState, step: Optional[int] = None) -> None:
    """One-shot save (no rotation bookkeeping)."""
    ckptr = ocp.StandardCheckpointer()
    step = int(state.step) if step is None else int(step)
    ckptr.save(os.path.join(os.path.abspath(directory), str(step)), state, force=True)
    ckptr.wait_until_finished()


def restore_train_state(directory: str, target: TrainState, step: Optional[int] = None) -> TrainState:
    """One-shot restore; picks the highest-numbered step if unspecified."""
    directory = os.path.abspath(directory)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise NoCheckpointError(
                f"no checkpoints under {directory} (cold start: "
                "initialize fresh state instead of restoring)"
            )
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.join(directory, str(step)), target)
