"""Socket helpers: master discovery + length-prefixed wire framing.

Reference: ``elephas/utils/sockets.py::{determine_master, send, receive}``
(SURVEY.md §2.1) — the reference frames pickled Python objects with a
length prefix over raw TCP and discovers the driver endpoint via
``socket.gethostbyname(gethostname())``.

Here the same framing carries *control-plane* traffic only (async-mode
deltas between hosts, trial dispatch). Tensor data between chips rides ICI
via XLA collectives (SURVEY.md §2.3) and never touches these sockets on
the single-host path. Frames are ``!Q``-length-prefixed pickles; pickle is
acceptable because every endpoint is part of the same trusted job (same
trust model as the reference and as Spark's closure shipping).
"""

from __future__ import annotations

import pickle
import socket
import struct

_LEN = struct.Struct("!Q")


def host_ip() -> str:
    """This host's outward-facing IP.

    UDP-connect trick: "connecting" a datagram socket to any external
    address selects the routable local interface without sending a packet.
    ``gethostbyname(gethostname())`` — the reference's approach — returns
    127.0.1.1 on many Linux hosts (an /etc/hosts alias), which other hosts
    can't dial; this avoids that failure mode and needs no actual network
    reachability.
    """
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # never sent; routing only
            ip = s.getsockname()[0]
        if not ip.startswith("127."):
            return ip
    except OSError:
        pass
    try:  # fall back to resolver, rejecting loopback aliases
        ip = socket.gethostbyname(socket.gethostname())
        if not ip.startswith("127."):
            return ip
    except socket.gaierror:
        pass
    return "127.0.0.1"


def determine_master(port: int = 4000) -> str:
    """Return ``"<host_ip>:<port>"`` for the driver/host-0 endpoint.

    Mirrors the reference's ``determine_master``; used to embed the
    parameter-server address into worker closures.
    """
    return f"{host_ip()}:{port}"


def send(sock: socket.socket, obj) -> None:
    """Pickle ``obj`` and send it with an 8-byte length prefix."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def receive(sock: socket.socket):
    """Receive one length-prefixed pickled object (inverse of ``send``)."""
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, length))
