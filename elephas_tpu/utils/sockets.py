"""Socket helpers: master discovery + length-prefixed wire framing.

Reference: ``elephas/utils/sockets.py::{determine_master, send, receive}``
(SURVEY.md §2.1) — the reference frames pickled Python objects with a
length prefix over raw TCP and discovers the driver endpoint via
``socket.gethostbyname(gethostname())``.

Here the same framing carries *control-plane* traffic only (async-mode
deltas between hosts, trial dispatch). Tensor data between chips rides ICI
via XLA collectives (SURVEY.md §2.3) and never touches these sockets on
the single-host path. Frames are ``!Q``-length-prefixed pickles — or,
for the parameter-server hot path, pre-encoded packed-codec payloads
(``RawPayload``) recognized by magic bytes and sent/received without a
pickle round-trip or a full-frame copy. Because
``pickle.loads`` on attacker bytes is code execution, frames can carry an
HMAC-SHA256 tag (``key=``): the receiver verifies the tag BEFORE
unpickling and treats a mismatch as a connection error. Multi-host runs
turn this on by default with a secret broadcast over the DCN control
plane (async engine); keyless framing matches the reference's
same-trusted-job model and stays the single-host loopback default.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import socket
import struct
import threading
import time

_LEN = struct.Struct("!Q")
_MAC_LEN = 32  # HMAC-SHA256 digest size
_NONCE_LEN = 16
_TS = struct.Struct("!d")
_AUTH_HDR_LEN = _NONCE_LEN + _TS.size

# Packed-codec frame magics (defined HERE, not in parameter/wire.py,
# because wire.py imports this module — parameter/__init__ pulls in
# server/client which pull in sockets). Pickle protocol ≥2 bodies start
# with b"\x80", so sniffing these ASCII magics can never misclassify a
# legacy pickle peer's frame.
MAGIC_TREE = b"EPK1"  # packed tensor tree (parameter.wire.encode_tree)
MAGIC_NOTMOD = b"EPNM"  # tiny "not modified since version" reply
MAGIC_REJECT = b"EPRJ"  # typed "delta rejected: too stale" push reply
MAGIC_KV = b"EPKV"  # KV-block handoff frame (parameter.wire.encode_kv_blocks)
_PACKED_MAGICS = (MAGIC_TREE, MAGIC_NOTMOD, MAGIC_REJECT, MAGIC_KV)

_SEND_CHUNK = 1 << 20  # slice large buffers so no send stages a huge copy

# -- deterministic fault injection (resilience.faults) ------------------------
#
# A process-wide injector consulted once per frame in send()/receive().
# None (the default) costs a single attribute check; chaos tests install
# a resilience.faults.FaultInjector whose FaultPlan decides — purely
# from (seed, label, frame_seq) — whether this frame is dropped (raises
# ConnectionError at the injection site, the wire model of a lost
# frame/partition), delayed, or duplicated (the frame bytes are sent
# twice; with auth the peer's ReplayGuard rejects the copy, without it
# the duplicate double-applies — both are behaviors worth testing).
_fault_injector = None


def set_fault_injector(injector) -> None:
    """Install/clear (None) the process-wide wire fault injector."""
    global _fault_injector
    _fault_injector = injector


class RawPayload:
    """A pre-encoded wire payload as scatter-gather ``chunks``.

    ``send`` ships a ``RawPayload`` WITHOUT pickling and WITHOUT
    concatenating header+MAC+payload into one throwaway ``bytes`` — the
    MAC is computed incrementally over the chunks and each chunk goes to
    the socket as a ``memoryview`` slice. ``receive`` hands packed
    payloads (recognized by magic) back as raw bytes for the caller's
    codec; anything else is treated as legacy pickle.
    """

    __slots__ = ("chunks", "nbytes")

    def __init__(self, chunks):
        self.chunks = [
            c if isinstance(c, memoryview) else memoryview(c) for c in chunks
        ]
        self.nbytes = sum(c.nbytes for c in self.chunks)


def frame_mac(key: bytes, payload: bytes) -> bytes:
    """HMAC-SHA256 tag for one wire payload."""
    return hmac.new(key, payload, hashlib.sha256).digest()


def chunks_mac(key: bytes, parts) -> bytes:
    """HMAC-SHA256 over a sequence of buffers without concatenating."""
    mac = hmac.new(key, digestmod=hashlib.sha256)
    for part in parts:
        mac.update(part)
    return mac.digest()


def _sendall_chunks(sock: socket.socket, chunks) -> None:
    """Write each chunk, slicing big ones so no full-frame copy is staged."""
    for chunk in chunks:
        view = chunk if isinstance(chunk, memoryview) else memoryview(chunk)
        if view.nbytes <= _SEND_CHUNK:
            sock.sendall(view)
            continue
        view = view.cast("B")
        for off in range(0, view.nbytes, _SEND_CHUNK):
            sock.sendall(view[off:off + _SEND_CHUNK])


class ReplayGuard:
    """Reject duplicate or stale authenticated frames (server side).

    An HMAC alone authenticates the SENDER, not the OCCASION: a captured
    update/barrier frame replays verbatim and would double-apply. Each
    authenticated frame therefore carries a random nonce + wall-clock
    timestamp under the MAC; the receiver rejects frames outside the
    freshness ``window`` (hosts in one job are NTP-close; 300s is
    generous) and nonces it has already seen within it. Nonce memory is
    bounded by pruning expired entries."""

    def __init__(self, window: float = 300.0):
        from collections import deque

        self.window = window
        self._seen: set = set()
        self._order = deque()  # (expiry, nonce) in arrival order
        self._lock = threading.Lock()

    def check(self, nonce: bytes, ts: float) -> None:
        now = time.time()
        if abs(now - ts) > self.window:
            raise ConnectionError(
                "authenticated frame outside the replay-freshness window"
            )
        with self._lock:
            # Amortized O(1): expiries arrive ROUGHLY in order, so popping
            # the stale front is all the pruning ever needed (a wholesale
            # rebuild per frame would make a busy PS CPU-bound). A frame
            # from a fast-clocked sender can append a later expiry than
            # its successors, which only DELAYS pruning of those entries
            # (bounded by the window) — never drops a live nonce early.
            while self._order and self._order[0][0] <= now:
                self._seen.discard(self._order.popleft()[1])
            if nonce in self._seen:
                raise ConnectionError("replayed authenticated frame rejected")
            self._seen.add(nonce)
            # Retain until the frame could no longer pass the freshness
            # check above (advisor r4): a sender whose clock is AHEAD by S
            # passes freshness until ts + window, so expiring its nonce at
            # now + window would open an S-second replay gap.
            self._order.append((max(now, ts) + self.window, nonce))


def host_ip() -> str:
    """This host's outward-facing IP.

    UDP-connect trick: "connecting" a datagram socket to any external
    address selects the routable local interface without sending a packet.
    ``gethostbyname(gethostname())`` — the reference's approach — returns
    127.0.1.1 on many Linux hosts (an /etc/hosts alias), which other hosts
    can't dial; this avoids that failure mode and needs no actual network
    reachability.
    """
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # never sent; routing only
            ip = s.getsockname()[0]
        if not ip.startswith("127."):
            return ip
    except OSError:
        pass
    try:  # fall back to resolver, rejecting loopback aliases
        ip = socket.gethostbyname(socket.gethostname())
        if not ip.startswith("127."):
            return ip
    except socket.gaierror:
        pass
    return "127.0.0.1"


def determine_master(port: int = 4000) -> str:
    """Return ``"<host_ip>:<port>"`` for the driver/host-0 endpoint.

    Mirrors the reference's ``determine_master``; used to embed the
    parameter-server address into worker closures.
    """
    return f"{host_ip()}:{port}"


def send(
    sock: socket.socket, obj, key: bytes | None = None, bind: bytes = b""
) -> bytes:
    """Pickle ``obj`` and send it with an 8-byte length prefix; with
    ``key``, the frame is [mac32][nonce16][ts8][payload] with the
    HMAC-SHA256 tag covering bind+nonce+ts+payload (see ``ReplayGuard``).

    ``bind`` mixes extra context under the MAC without shipping it —
    servers bind replies to the REQUEST's nonce so a captured response
    can't be replayed into a later exchange (the receiver must pass the
    same ``bind``). Returns this frame's nonce (b"" when keyless) so
    callers can bind the reply they are about to read.

    A ``RawPayload`` (packed-codec frames) is sent as-is: its chunks go
    out as memoryview slices after the small length/MAC/nonce prefix —
    the payload is never copied into a contiguous frame, and the MAC is
    computed incrementally over the same chunks."""
    action = "pass"
    if _fault_injector is not None:
        # May raise ConnectionError (planned drop/partition) or sleep
        # (planned delay) BEFORE anything hits the wire — the peer never
        # sees a dropped frame, exactly like a lost packet.
        action = _fault_injector.on_send(sock)
    if isinstance(obj, RawPayload):
        chunks, payload_len = obj.chunks, obj.nbytes
    else:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        chunks, payload_len = [payload], len(payload)
    if key is not None:
        nonce = os.urandom(_NONCE_LEN)
        auth_hdr = nonce + _TS.pack(time.time())
        mac = chunks_mac(key, [bind, auth_hdr, *chunks])
        prefix = _LEN.pack(payload_len + _AUTH_HDR_LEN + _MAC_LEN) + mac + auth_hdr
        _sendall_chunks(sock, [prefix, *chunks])
        if action == "dup":  # byte-identical duplicate (same nonce):
            _sendall_chunks(sock, [prefix, *chunks])
        return nonce
    _sendall_chunks(sock, [_LEN.pack(payload_len), *chunks])
    if action == "dup":
        _sendall_chunks(sock, [_LEN.pack(payload_len), *chunks])
    return b""


def _recv_exact(sock: socket.socket, n: int) -> memoryview:
    """Read exactly ``n`` bytes into ONE preallocated buffer (recv_into,
    no chunk-list join copy) and return a read-write view of it."""
    buf = bytearray(n)
    view = memoryview(buf)
    pos = 0
    while pos < n:
        got = sock.recv_into(view[pos:], min(n - pos, 1 << 20))
        if not got:
            raise ConnectionError("socket closed mid-frame")
        pos += got
    return view


def _loads_or_raw(payload: memoryview):
    """Magic-byte negotiation: packed-codec payloads come back RAW
    (bytes-like, for ``parameter.wire.decode``); anything else is a
    legacy pickle frame and is unpickled here. Only called AFTER the
    HMAC check when a key is configured."""
    if bytes(payload[:4]) in _PACKED_MAGICS:
        return payload
    return pickle.loads(payload)


def receive(
    sock: socket.socket,
    key: bytes | None = None,
    replay_guard: ReplayGuard | None = None,
    bind: bytes = b"",
    return_nonce: bool = False,
):
    """Receive one length-prefixed pickled object (inverse of ``send``).

    With ``key``, the frame's HMAC tag is verified BEFORE any payload
    decode — unauthenticated or tampered bytes never reach
    ``pickle.loads`` (and never reach the packed codec either; the
    magic sniff below happens strictly after the MAC check).
    ``replay_guard`` (servers) additionally rejects duplicate/stale
    nonces under the MAC. ``bind`` must match the sender's (clients pass
    their request nonce when reading the reply). ``return_nonce=True``
    returns ``(obj, nonce)`` so servers can bind their reply.

    Packed-codec payloads (``MAGIC_TREE``/``MAGIC_NOTMOD``) are returned
    as raw bytes-like views for ``parameter.wire`` to decode zero-copy;
    everything else unpickles as before."""
    if _fault_injector is not None:
        # A planned recv drop models the reply lost in flight: raise
        # before reading so the caller's connection-error path runs.
        _fault_injector.on_recv(sock)
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    data = _recv_exact(sock, length)
    if key is not None:
        if length < _MAC_LEN + _AUTH_HDR_LEN:
            raise ConnectionError("authenticated frame shorter than its header")
        tag, body = data[:_MAC_LEN], data[_MAC_LEN:]
        if not hmac.compare_digest(tag, chunks_mac(key, [bind, body])):
            raise ConnectionError(
                "wire-frame authentication failed (bad or missing HMAC)"
            )
        nonce = bytes(body[:_NONCE_LEN])
        (ts,) = _TS.unpack(body[_NONCE_LEN:_AUTH_HDR_LEN])
        if replay_guard is not None:
            replay_guard.check(nonce, ts)
        obj = _loads_or_raw(body[_AUTH_HDR_LEN:])
        return (obj, nonce) if return_nonce else obj
    obj = _loads_or_raw(data)
    return (obj, b"") if return_nonce else obj
