"""Runtime lock sanitizer: the dynamic half of the concurrency lint.

``elephas_tpu/analysis/locks.py`` derives the package's lock-acquisition
graph statically; this module cross-validates it on REAL executions.
When enabled (``ELEPHAS_LOCK_SANITIZER=1``, or :func:`enable` from a
test fixture), the :func:`make_lock`/:func:`make_rlock`/
:func:`make_condition` factories hand out :class:`InstrumentedLock`
wrappers that record, per thread, the order locks are taken in. Every
blocking acquisition is checked against the union of (a) the statically
derived order seeded from ``ANALYSIS.json`` and (b) every order observed
so far in this process: acquiring ``B`` while holding ``A`` when ``B``
can already reach ``A`` in that graph is an inversion — two threads
interleaving those paths can deadlock — and raises
:class:`LockOrderInversion` at the acquisition site instead of hanging a
CI run. A same-thread re-acquire of a non-reentrant lock raises too
(that hang needs no second thread).

Deliberate exemptions mirror the static analyzer: a NONBLOCKING
``acquire(blocking=False)`` is the sanctioned order-breaking pattern
(try-lock either succeeds or backs off — it cannot deadlock) and adds
no edge; ``Condition.wait`` fully releases its lock, so the held stack
is popped around the wait.

Disabled (the default), the factories return plain
``threading.Lock``/``RLock``/``Condition`` objects — the production
path carries zero wrapper overhead, which the unit tests pin by type.

Lock NAMES are the contract: ``make_lock("ParameterBuffer._version_guard")``
must use the identity the static analyzer derives for that field — the
``lock-order`` rule fails on drift, so the two graphs always join.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, List, Optional, Set, Tuple


class LockOrderInversion(RuntimeError):
    """Raised at an acquisition that inverts the established order."""


class _Registry:
    """Process-wide order graph + per-thread held stacks."""

    def __init__(self):
        self._mu = threading.Lock()
        self._observed: Dict[str, Set[str]] = {}
        self._static: Dict[str, Set[str]] = {}
        self._tls = threading.local()
        self.blocking_events: List[Tuple[Tuple[str, ...], str, str]] = []
        self.checks = 0          # acquisitions order-checked (telemetry)

    # -- per-thread stack ----------------------------------------------------

    def held(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    # -- graph ---------------------------------------------------------------

    def load_static_order(self, edges: Iterable[Tuple[str, str]]) -> None:
        with self._mu:
            for src, dst in edges:
                self._static.setdefault(src, set()).add(dst)

    def load_analysis(self, path) -> int:
        """Seed from a committed ``ANALYSIS.json``; returns edge count
        (0 when the file is absent — sanitizing still works from
        observed orders alone)."""
        import json
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError):
            return 0
        edges = [(e["src"], e["dst"])
                 for e in report.get("lock_graph", {}).get("edges", [])]
        self.load_static_order(edges)
        return len(edges)

    def _reaches(self, src: str, dst: str) -> Optional[List[str]]:
        """Path ``src -> … -> dst`` in observed ∪ static, else None.
        Caller holds ``_mu``."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in (self._observed.get(node, set())
                        | self._static.get(node, set())):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- events --------------------------------------------------------------

    def note_acquire(self, name: str, reentrant: bool = False) -> None:
        """Order-check + record a BLOCKING acquisition about to happen.
        Raises instead of letting the caller deadlock."""
        held = self.held()
        if name in held:
            if reentrant:
                held.append(name)
                return
            raise LockOrderInversion(
                f"self-deadlock: {threading.current_thread().name} "
                f"re-acquires non-reentrant lock {name} it already holds")
        with self._mu:
            self.checks += 1
            for h in reversed(held):
                path = self._reaches(name, h)
                if path is not None:
                    raise LockOrderInversion(
                        f"lock-order inversion: "
                        f"{threading.current_thread().name} acquires "
                        f"{name} while holding {h}, but the established "
                        f"order is {' -> '.join(path)}")
            for h in held:
                self._observed.setdefault(h, set()).add(name)
        held.append(name)

    def note_tryacquire(self, name: str) -> None:
        """A successful nonblocking acquire: hold-tracked (so blocking
        events under it are attributed) but never order-checked — the
        try-lock pattern is deadlock-free by construction."""
        self.held().append(name)

    def note_release(self, name: str) -> None:
        held = self.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def note_blocking(self, desc: str,
                      exclude: Optional[str] = None) -> None:
        held = tuple(h for h in self.held() if h != exclude)
        if held:
            self.blocking_events.append(
                (held, desc, threading.current_thread().name))

    def snapshot_edges(self) -> Dict[str, Set[str]]:
        with self._mu:
            return {k: set(v) for k, v in self._observed.items()}


_REGISTRY = _Registry()
_ENABLED = os.environ.get("ELEPHAS_LOCK_SANITIZER", "") == "1"


def enabled() -> bool:
    return _ENABLED


def registry() -> _Registry:
    return _REGISTRY


def enable(analysis_path=None) -> None:
    """Turn the sanitizer on (test fixtures; prod uses the env var).
    Starts from a FRESH registry so one test's observed orders don't
    leak into the next; ``analysis_path`` seeds the static order."""
    global _ENABLED, _REGISTRY
    _REGISTRY = _Registry()
    if analysis_path is not None:
        _REGISTRY.load_analysis(analysis_path)
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


class InstrumentedLock:
    """``threading.Lock``/``RLock`` wrapper that feeds the registry."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not blocking:
            got = self._inner.acquire(False)
            if got:
                _REGISTRY.note_tryacquire(self.name)
            return got
        _REGISTRY.note_acquire(self.name, self.reentrant)  # may raise
        try:
            got = self._inner.acquire(True, timeout)
        except BaseException:
            _REGISTRY.note_release(self.name)
            raise
        if not got:
            _REGISTRY.note_release(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        _REGISTRY.note_release(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<InstrumentedLock {self.name}>"


class InstrumentedCondition(threading.Condition):
    """Condition over an :class:`InstrumentedLock`; ``wait`` records a
    held-while-blocking event when OTHER locks are held across it (the
    cond's own lock is released by the wait protocol — the default
    ``_release_save`` calls our ``release``, popping the stack)."""

    def __init__(self, name: str):
        self.name = name
        super().__init__(InstrumentedLock(name))

    def wait(self, timeout: Optional[float] = None) -> bool:
        _REGISTRY.note_blocking(f"cond.wait({self.name})",
                                exclude=self.name)
        return super().wait(timeout)


def make_lock(name: str):
    """A mutex: plain ``threading.Lock`` disabled (zero overhead),
    instrumented under the sanitizer. ``name`` must be the statically
    derived identity (``Class.attr`` / ``module.attr``)."""
    if _ENABLED:
        return InstrumentedLock(name)
    return threading.Lock()


def make_rlock(name: str):
    if _ENABLED:
        return InstrumentedLock(name, reentrant=True)
    return threading.RLock()


def make_condition(name: str):
    if _ENABLED:
        return InstrumentedCondition(name)
    return threading.Condition()


def note_blocking(desc: str) -> None:
    """Optional hook for call sites that KNOW they block (journal
    fsync, socket round-trip): records a held-while-blocking event when
    sanitizing, free no-op otherwise."""
    if _ENABLED:
        _REGISTRY.note_blocking(desc)


def rw_acquire(name: Optional[str], write: bool) -> None:
    """RWLock integration: order-check the SEMANTIC lock identity
    before the RWLock blocks on its internal condition. Read sides are
    shared, so a same-thread nested read is reentrant; a same-thread
    write-while-held is a real self-deadlock (the writer waits for its
    own read/write to drain) and raises."""
    if name is not None and _ENABLED:
        _REGISTRY.note_acquire(name, reentrant=not write)


def rw_release(name: Optional[str]) -> None:
    if name is not None and _ENABLED:
        _REGISTRY.note_release(name)
