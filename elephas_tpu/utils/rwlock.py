"""Readers-writer lock.

Reference: ``elephas/utils/rwlock.py::RWLock`` (SURVEY.md §2.1, §5.2) —
guards the parameter-server weight state in ``asynchronous`` mode and is
deliberately bypassed in ``hogwild`` mode (lock-free, Hogwild!-style).

This implementation is writer-preferring: once a writer is waiting, new
readers queue behind it, so pull-heavy Downpour loops cannot starve the
merge thread. The reference exposes ``acquire_read`` / ``acquire_write`` /
``release``; we keep those names and add context-manager helpers, which is
what the engine uses internally.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from elephas_tpu.utils import locksan


class RWLock:
    """Writer-preferring readers-writer lock with the reference's API.

    ``name`` opts the lock into the runtime sanitizer
    (:mod:`elephas_tpu.utils.locksan`) under its STATIC identity (the
    ``Class.attr`` the analyzer derives); the whole RWLock is one node
    in the order graph regardless of read/write side. The internal
    condition stays untracked — it is released before any user code
    runs, so it can never participate in an inversion.
    """

    def __init__(self, name: str | None = None):
        self._cond = threading.Condition(threading.Lock())
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0
        self._san_name = name

    def acquire_read(self):
        locksan.rw_acquire(self._san_name, write=False)
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def acquire_write(self):
        locksan.rw_acquire(self._san_name, write=True)
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def release(self):
        """Release whichever side the calling thread holds."""
        with self._cond:
            if self._writer:
                self._writer = False
            elif self._readers:
                self._readers -= 1
            else:
                raise RuntimeError("release() without a held lock")
            self._cond.notify_all()
        locksan.rw_release(self._san_name)

    @contextmanager
    def reading(self):
        self.acquire_read()
        try:
            yield self
        finally:
            self.release()

    @contextmanager
    def writing(self):
        self.acquire_write()
        try:
            yield self
        finally:
            self.release()


class NullLock:
    """Lock-shaped no-op used by ``hogwild`` mode (SURVEY.md §2.2).

    Updates proceed unfenced / last-writer-wins. On the host side the
    CPython GIL still serializes the actual pointer swap, so "race" here
    means interleaved read-modify-write at the pytree level — exactly the
    Hogwild! algorithmic contract, not memory corruption.
    """

    def acquire_read(self):
        pass

    def acquire_write(self):
        pass

    def release(self):
        pass

    @contextmanager
    def reading(self):
        yield self

    @contextmanager
    def writing(self):
        yield self
