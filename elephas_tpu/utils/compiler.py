"""Backend compile options for the hot jitted programs.

One measured knob so far: ``xla_tpu_scoped_vmem_limit_kib``. Raising
XLA's scoped-VMEM budget from its default to 96 MiB bought a consistent
+4–5% on the flagship ResNet-18 train step (33.8k → 35.4k samples/sec,
2×40-step repeats, r4 sweep — other candidate options measured at noise
level), by giving fusions deeper VMEM buffering. Verified compatible
with the Pallas flash-attention kernels (their scratch is declared per
``pallas_call``, not from this scope): the 8k flash fwd+bwd and a
4k-seq flash LM train step both compile and run under the option.

``$ELEPHAS_SCOPED_VMEM_KIB`` overrides the budget; ``0`` disables the
option entirely (compile with backend defaults — the escape hatch if a
future model's VMEM footprint collides).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

logger = logging.getLogger("elephas_tpu")

_DEFAULT_KIB = 98304  # 96 MiB — r4 sweep winner on v5-lite


def tpu_compiler_options() -> Optional[dict]:
    """Compiler options for jitting hot train/eval programs.

    Returns None off-TPU (and when disabled with ``0``), so CPU tests
    and other backends compile exactly as before. A malformed override
    falls back to the default WITH a warning — silently dropping the
    option would be a quiet ~4–5% regression with nothing in the logs.
    """
    if jax.default_backend() != "tpu":
        return None
    kib = os.environ.get("ELEPHAS_SCOPED_VMEM_KIB", str(_DEFAULT_KIB))
    try:
        value = int(kib)
    except ValueError:
        logger.warning(
            "ELEPHAS_SCOPED_VMEM_KIB=%r is not an integer; using the "
            "default %d KiB (set 0 to disable)", kib, _DEFAULT_KIB,
        )
        value = _DEFAULT_KIB
    if value <= 0:
        return None
    return {"xla_tpu_scoped_vmem_limit_kib": str(value)}
