"""Backend compile options for the hot jitted programs.

One measured knob so far: ``xla_tpu_scoped_vmem_limit_kib`` (reachable
only via ``jax.jit(..., compiler_options=...)`` — this build's
``XLA_FLAGS`` parser rejects TPU flags). The r4 sweep measured raising
the scoped-VMEM budget to 96 MiB at **+4–5% on the bare flagship
ResNet-18 train step** (33.8k → 35.3k samples/sec, repeated 40-step
runs) — but a per-workload A/B on the real parity fits showed it is NOT
a safe default:

| workload (full fit, steady) | default | 96 MiB |
|---|---|---|
| CIFAR ResNet-18 hogwild | 33.3k | 32.1k (−3%) |
| MNIST CNN async         | 65.1k | 65.5k (neutral) |
| IMDB LSTM estimator     | 34.9k | **19.9k (−43%)** |

The scan-heavy LSTM regresses catastrophically, and the gains on the
bare conv step do not survive the real fit. The knob therefore ships
OPT-IN: set ``$ELEPHAS_SCOPED_VMEM_KIB`` (e.g. ``98304``) to apply it
to every hot program (train/eval/predict across all trainers, bench,
and sweeps — they share this helper so measurements match production);
unset or ``0`` compiles with backend defaults.
"""

from __future__ import annotations

import collections
import logging
import os
import time
from typing import Dict, Optional

import jax

logger = logging.getLogger("elephas_tpu")

# Retrace-storm detection: a hot program retracing this many times
# inside the window is no longer "a shape changed once" — something is
# feeding it fresh shapes/dtypes per call and silently recompiling on
# the hot path. The 4th retrace in 60s files a flight-recorder anomaly.
_RETRACE_STORM_COUNT = 4
_RETRACE_STORM_WINDOW_S = 60.0
_retrace_times: Dict[str, collections.deque] = {}


def note_retrace(program: str, **args) -> None:
    """Record a (re)trace of a hot program on the global observability
    layer: a ``retrace_total{program=...}`` counter bump and an instant
    ``compile/<program>`` event on the default tracer.

    Call this from inside a jitted function's Python body — the body
    only runs when XLA (re)traces it, so a surprise retrace (a silent
    10× regression when it happens per step) becomes a visible counter
    and a trace marker instead of nothing. The serving engine wires its
    prefill/decode bodies through here; tests pin those at one trace
    each. Repeated retraces of one program inside a short window are a
    *retrace storm* and additionally land in the flight recorder.
    """
    from elephas_tpu import obs

    obs.default_registry().counter(
        "retrace_total",
        help="hot-program (re)traces across the process",
        labelnames=("program",),
    ).labels(program=program).inc()
    obs.default_tracer().instant(f"compile/{program}", **args)
    now = time.monotonic()
    times = _retrace_times.setdefault(
        program, collections.deque(maxlen=_RETRACE_STORM_COUNT))
    times.append(now)
    if (len(times) == _RETRACE_STORM_COUNT
            and now - times[0] <= _RETRACE_STORM_WINDOW_S):
        obs.default_flight_recorder().note(
            "retrace_storm", "warn", program=program,
            retraces=_RETRACE_STORM_COUNT,
            window_s=round(now - times[0], 3),
        )
    logger.debug("retrace: %s %s", program, args or "")


def tpu_compiler_options() -> Optional[dict]:
    """Compiler options for jitting hot train/eval programs.

    None (backend defaults) unless ``$ELEPHAS_SCOPED_VMEM_KIB`` opts in;
    always None off-TPU. A malformed value warns and is ignored rather
    than silently changing compile behavior.
    """
    if jax.default_backend() != "tpu":
        return None
    kib = os.environ.get("ELEPHAS_SCOPED_VMEM_KIB")
    if not kib:
        return None
    try:
        value = int(kib)
    except ValueError:
        logger.warning(
            "ELEPHAS_SCOPED_VMEM_KIB=%r is not an integer; compiling with "
            "backend defaults", kib,
        )
        return None
    if value <= 0:
        return None
    return {"xla_tpu_scoped_vmem_limit_kib": str(value)}


# The measured-separable candidate (see module docstring's A/B table):
# +4–5% on conv-heavy steps, −43% on the scan-heavy LSTM — exactly why a
# MEASUREMENT per workload, not a default, must pick it.
_SCOPED_VMEM_CANDIDATE_KIB = 98304


def autotune_candidates():
    """``[(label, compiler_options)]`` worth A/B-ing for a hot program.

    One entry (nothing to tune) off-TPU or when the user already forced
    an option set via ``$ELEPHAS_SCOPED_VMEM_KIB`` — an explicit choice
    always wins over the autotuner, and is LABELED as such so the
    recorded ``compile_autotune`` never claims 'default' for a fit that
    actually compiled with the forced knob."""
    if jax.default_backend() != "tpu":
        return [("default", None)]
    base = tpu_compiler_options()
    if base is not None:
        return [("env_forced", base)]
    return [
        ("default", None),
        (
            "scoped_vmem_96m",
            {"xla_tpu_scoped_vmem_limit_kib": str(_SCOPED_VMEM_CANDIDATE_KIB)},
        ),
    ]


def autotune_compile_options(build, run, force, steps: int = 24, candidates=None):
    """One-shot per-workload compile-option A/B (VERDICT r4 #5).

    ``build(opts) -> fn`` compiles the workload's hot program with one
    candidate's options; ``run(fn) -> out`` DISPATCHES it once
    (no blocking); ``force(out)`` makes its result real (fetch a
    scalar — on the tunneled dev chip ``block_until_ready`` lies).
    Each candidate is compiled, warmed with one forced run, then timed
    over ``steps`` dispatches with ONE trailing force — a force per
    step would bill a host↔device round-trip (~50–90ms through the dev
    tunnel) to every step and drown the per-step signal the A/B exists
    to read. The fastest candidate wins.

    Returns ``(winner_label, winner_options, ms_per_step_table)``.
    With a single candidate (off-TPU / env-forced) nothing is timed —
    the only candidate is returned with an empty table, so callers can
    gate unconditionally on ``autotune=True``.
    """
    import time

    from elephas_tpu import obs

    if candidates is None:
        candidates = autotune_candidates()
    if len(candidates) == 1:
        label, opts = candidates[0]
        return label, opts, {}
    table = {}
    by_label = {}
    tracer = obs.default_tracer()
    for label, opts in candidates:
        with tracer.span(f"compile/autotune:{label}"):
            fn = build(opts)
            force(run(fn))  # compile + warm
        t0 = time.perf_counter()
        out = None
        for _ in range(steps):
            out = run(fn)
        force(out)
        table[label] = (time.perf_counter() - t0) / steps * 1e3
        by_label[label] = opts
    winner = min(table, key=table.get)
    logger.info(
        "compile autotune: %r wins — %s",
        winner,
        {k: f"{v:.2f}ms" for k, v in table.items()},
    )
    return winner, by_label[winner], table
