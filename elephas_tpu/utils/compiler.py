"""Backend compile options for the hot jitted programs.

One measured knob so far: ``xla_tpu_scoped_vmem_limit_kib`` (reachable
only via ``jax.jit(..., compiler_options=...)`` — this build's
``XLA_FLAGS`` parser rejects TPU flags). The r4 sweep measured raising
the scoped-VMEM budget to 96 MiB at **+4–5% on the bare flagship
ResNet-18 train step** (33.8k → 35.3k samples/sec, repeated 40-step
runs) — but a per-workload A/B on the real parity fits showed it is NOT
a safe default:

| workload (full fit, steady) | default | 96 MiB |
|---|---|---|
| CIFAR ResNet-18 hogwild | 33.3k | 32.1k (−3%) |
| MNIST CNN async         | 65.1k | 65.5k (neutral) |
| IMDB LSTM estimator     | 34.9k | **19.9k (−43%)** |

The scan-heavy LSTM regresses catastrophically, and the gains on the
bare conv step do not survive the real fit. The knob therefore ships
OPT-IN: set ``$ELEPHAS_SCOPED_VMEM_KIB`` (e.g. ``98304``) to apply it
to every hot program (train/eval/predict across all trainers, bench,
and sweeps — they share this helper so measurements match production);
unset or ``0`` compiles with backend defaults.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

logger = logging.getLogger("elephas_tpu")


def tpu_compiler_options() -> Optional[dict]:
    """Compiler options for jitting hot train/eval programs.

    None (backend defaults) unless ``$ELEPHAS_SCOPED_VMEM_KIB`` opts in;
    always None off-TPU. A malformed value warns and is ignored rather
    than silently changing compile behavior.
    """
    if jax.default_backend() != "tpu":
        return None
    kib = os.environ.get("ELEPHAS_SCOPED_VMEM_KIB")
    if not kib:
        return None
    try:
        value = int(kib)
    except ValueError:
        logger.warning(
            "ELEPHAS_SCOPED_VMEM_KIB=%r is not an integer; compiling with "
            "backend defaults", kib,
        )
        return None
    if value <= 0:
        return None
    return {"xla_tpu_scoped_vmem_limit_kib": str(value)}
