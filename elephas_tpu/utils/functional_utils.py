"""Weight/update algebra over pytrees.

Reference: ``elephas/utils/functional_utils.py::{add_params,
subtract_params, divide_by, get_neutral_vector}`` (SURVEY.md §2.1) — the
entire gradient-aggregation math of the reference, there implemented as
elementwise loops over Python lists of numpy arrays.

TPU-native redesign: parameters are arbitrary JAX pytrees (flax
``FrozenDict``s, plain dicts, lists), the ops are ``jax.tree_util`` maps
that jit/vmap cleanly and run on-device, so delta aggregation can live
inside a compiled step (e.g. under ``lax.psum``) instead of on a Python
driver. The reference's list-of-ndarray format is a special case of a
pytree, so the API is a strict superset.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def add_params(tree_a, tree_b):
    """Elementwise ``a + b`` over two matching pytrees of arrays."""
    return jax.tree_util.tree_map(jnp.add, tree_a, tree_b)


def subtract_params(tree_a, tree_b):
    """Elementwise ``a - b`` over two matching pytrees of arrays.

    ``subtract_params(before, after)`` is the reference's definition of a
    worker's weight *delta* (applied by the driver as ``base - mean_delta``).
    """
    return jax.tree_util.tree_map(jnp.subtract, tree_a, tree_b)


def divide_by(tree, num_workers):
    """Divide every leaf by a scalar (delta averaging)."""
    return jax.tree_util.tree_map(lambda x: x / num_workers, tree)


def scale_params(tree, factor):
    """Multiply every leaf by a scalar."""
    return jax.tree_util.tree_map(lambda x: x * factor, tree)


def get_neutral_vector(tree):
    """A zeros-like pytree — the neutral element of ``add_params``."""
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def average_params(trees):
    """Mean of a non-empty sequence of matching pytrees.

    Driver-side fold used by the synchronous parity path (the reference
    folds ``add_params`` over collected partition deltas then divides).
    On-device averaging uses ``lax.pmean`` instead — see
    ``elephas_tpu.engine.sync``.
    """
    if not trees:
        raise ValueError("average_params needs at least one pytree")
    total = trees[0]
    for tree in trees[1:]:
        total = add_params(total, tree)
    return divide_by(total, float(len(trees)))


def tree_size(tree):
    """Total number of scalar elements across all leaves."""
    return sum(leaf.size for leaf in jax.tree_util.tree_leaves(tree))


def global_norm(tree):
    """L2 norm over all leaves (diagnostics / staleness tests)."""
    leaves = [jnp.sum(jnp.square(leaf)) for leaf in jax.tree_util.tree_leaves(tree)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))
