"""Utility layer (reference: ``elephas/utils/`` — SURVEY.md §2.1 L1)."""
