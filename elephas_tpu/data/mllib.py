"""MLlib linalg adapters (reference: ``elephas/mllib/adapter.py``).

The reference converts between numpy and ``pyspark.mllib.linalg``
``Vector``/``Matrix`` types (``to_vector``/``from_vector``/``to_matrix``/
``from_matrix`` — SURVEY.md §2.1). pyspark is absent, so this module
defines the minimal dense types with the same accessors plus the four
conversion functions, keeping the ``SparkMLlibModel`` / LabeledPoint path
API-complete.
"""

from __future__ import annotations

import numpy as np


class DenseVector:
    """Dense vector with pyspark.mllib's accessor surface."""

    def __init__(self, values):
        self._values = np.asarray(values, dtype=np.float64).reshape(-1)

    def toArray(self) -> np.ndarray:  # noqa: N802
        return self._values

    @property
    def values(self) -> np.ndarray:
        return self._values

    def __len__(self) -> int:
        return len(self._values)

    def __eq__(self, other) -> bool:
        return isinstance(other, DenseVector) and np.array_equal(self._values, other._values)

    def __repr__(self) -> str:
        return f"DenseVector({self._values.tolist()})"


class DenseMatrix:
    """Dense matrix, column-major like pyspark.mllib (numRows, numCols, values)."""

    def __init__(self, num_rows: int, num_cols: int, values):
        values = np.asarray(values, dtype=np.float64).reshape(-1)
        if values.size != num_rows * num_cols:
            raise ValueError("values size does not match matrix shape")
        self.numRows = int(num_rows)  # noqa: N815 (pyspark parity)
        self.numCols = int(num_cols)  # noqa: N815
        self._values = values

    def toArray(self) -> np.ndarray:  # noqa: N802
        # pyspark stores column-major.
        return self._values.reshape(self.numCols, self.numRows).T

    @property
    def values(self) -> np.ndarray:
        return self._values

    def __repr__(self) -> str:
        return f"DenseMatrix({self.numRows}x{self.numCols})"


def to_vector(np_array: np.ndarray) -> DenseVector:
    """1-D numpy array -> DenseVector (reference ``to_vector``)."""
    arr = np.asarray(np_array)
    if arr.ndim != 1:
        raise ValueError(f"to_vector expects a 1-D array, got shape {arr.shape}")
    return DenseVector(arr)


def from_vector(vector: DenseVector) -> np.ndarray:
    """DenseVector -> numpy array (reference ``from_vector``)."""
    return vector.toArray()


def to_matrix(np_array: np.ndarray) -> DenseMatrix:
    """2-D numpy array -> DenseMatrix (reference ``to_matrix``)."""
    arr = np.asarray(np_array)
    if arr.ndim != 2:
        raise ValueError(f"to_matrix expects a 2-D array, got shape {arr.shape}")
    return DenseMatrix(arr.shape[0], arr.shape[1], arr.T.reshape(-1))


def from_matrix(matrix: DenseMatrix) -> np.ndarray:
    """DenseMatrix -> numpy array (reference ``from_matrix``)."""
    return matrix.toArray()
