"""Minimal columnar DataFrame for the ML-pipeline façade.

The reference's ``ElephasEstimator`` operates on ``pyspark.sql.DataFrame``
(SURVEY.md §3.3). pyspark does not exist here, so this module provides the
small columnar surface the pipeline actually uses: named columns of numpy
arrays, ``select``/``withColumn``, and conversion to/from pandas. It is a
deliberate *data structure*, not a query engine — Spark's distributed SQL
is L0 borrowing the rebuild does not need (compute distribution happens at
the ShardedDataset/mesh level instead).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class DataFrame:
    """Immutable named columns of equal-length numpy arrays."""

    def __init__(self, columns: Dict[str, np.ndarray]):
        if not columns:
            raise ValueError("DataFrame needs at least one column")
        lengths = {name: len(np.asarray(col)) for name, col in columns.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"column length mismatch: {lengths}")
        self._columns = {name: np.asarray(col) for name, col in columns.items()}

    # -- pyspark-flavored surface ---------------------------------------------

    @property
    def columns(self) -> List[str]:
        return list(self._columns)

    def __len__(self) -> int:
        return len(next(iter(self._columns.values())))

    def count(self) -> int:
        return len(self)

    def __getitem__(self, name: str) -> np.ndarray:
        return self._columns[name]

    def select(self, *names: str) -> "DataFrame":
        missing = [n for n in names if n not in self._columns]
        if missing:
            raise KeyError(f"unknown column(s): {missing}")
        return DataFrame({n: self._columns[n] for n in names})

    def with_column(self, name: str, values: np.ndarray) -> "DataFrame":
        new = dict(self._columns)
        new[name] = np.asarray(values)
        return DataFrame(new)

    # Spark camelCase alias used by reference-era user code.
    withColumn = with_column  # noqa: N815

    def drop(self, *names: str) -> "DataFrame":
        return DataFrame({n: c for n, c in self._columns.items() if n not in names})

    def limit(self, n: int) -> "DataFrame":
        return DataFrame({name: col[:n] for name, col in self._columns.items()})

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(
            {
                name: (list(col) if col.ndim > 1 else col)
                for name, col in self._columns.items()
            }
        )

    toPandas = to_pandas  # noqa: N815

    @staticmethod
    def from_pandas(pdf) -> "DataFrame":
        cols = {}
        for name in pdf.columns:
            values = pdf[name].to_numpy()
            if values.dtype == object:
                values = np.stack([np.asarray(v) for v in values])
            cols[name] = values
        return DataFrame(cols)

    def head(self, n: int = 5):
        return {name: col[:n] for name, col in self._columns.items()}

    def __repr__(self) -> str:
        shapes = {n: tuple(c.shape) for n, c in self._columns.items()}
        return f"DataFrame({shapes})"


def to_data_frame(sc, features: np.ndarray, labels: np.ndarray, categorical: bool = False) -> DataFrame:
    """Arrays -> DataFrame (reference ``elephas/ml/adapter.py::to_data_frame``).

    ``categorical=True`` means labels arrive one-hot and are stored as the
    scalar class index in the ``label`` column, like the reference.
    """
    del sc
    labels = np.asarray(labels)
    if categorical:
        label_col = np.argmax(labels, axis=-1).astype(np.float32)
    else:
        label_col = np.squeeze(labels).astype(np.float32)
    return DataFrame({"features": np.asarray(features), "label": label_col})


def from_data_frame(
    df: DataFrame,
    categorical: bool = False,
    nb_classes: Optional[int] = None,
    features_col: str = "features",
    label_col: str = "label",
):
    """DataFrame -> (features, labels) arrays (reference ``from_data_frame``)."""
    features = df[features_col]
    labels = df[label_col]
    if categorical:
        from elephas_tpu.native import encode_onehot

        if nb_classes is None:
            nb_classes = int(labels.max()) + 1
        int_labels = labels.astype(np.int64)
        if int_labels.size and (int_labels.min() < 0 or int_labels.max() >= nb_classes):
            raise ValueError(
                f"labels outside [0, {nb_classes}): "
                f"min={int_labels.min()}, max={int_labels.max()}"
            )
        labels = encode_onehot(int_labels, nb_classes)
    return features, labels


def df_to_simple_rdd(
    df: DataFrame,
    categorical: bool = False,
    nb_classes: Optional[int] = None,
    features_col: str = "features",
    label_col: str = "label",
    num_partitions: int = 1,
):
    """DataFrame -> ShardedDataset (reference ``df_to_simple_rdd``)."""
    from elephas_tpu.data.rdd import ShardedDataset

    features, labels = from_data_frame(df, categorical, nb_classes, features_col, label_col)
    return ShardedDataset(features, labels, num_partitions)
