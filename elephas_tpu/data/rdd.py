"""ShardedDataset — the TPU-native equivalent of the reference's RDDs.

Reference: ``elephas/utils/rdd_utils.py::{to_simple_rdd, to_labeled_point,
from_labeled_point, lp_to_simple_rdd, encode_label}`` (SURVEY.md §2.1).

In the reference, training data is a Spark RDD of ``(features, label)``
numpy pairs and each RDD *partition* becomes one worker's shard; Spark
owns placement. Here the same contract is a ``ShardedDataset``: features
and labels held as contiguous numpy arrays plus an explicit partition map,
so that

- partition ``i`` maps to device ``i % n_devices`` (sync/async engines),
- ``shard_batch`` materializes a global batch as a single
  ``jax.Array`` sharded over the mesh's ``'data'`` axis (so a jitted step
  sees one global array and XLA keeps each shard local to its chip), and
- partition-faithful iteration (``partition(i)``) reproduces the
  reference's per-worker local-training semantics for parity tests.

No Spark driver exists, so ``to_simple_rdd(sc, ...)`` keeps its reference
signature with ``sc`` accepted-and-ignored (pass ``None``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np


@dataclass
class LabeledPoint:
    """Minimal stand-in for ``pyspark.mllib.regression.LabeledPoint``."""

    label: float
    features: np.ndarray


def encode_label(label, nb_classes: int) -> np.ndarray:
    """One-hot encode a scalar label (reference ``encode_label``)."""
    out = np.zeros(nb_classes, dtype=np.float32)
    out[int(label)] = 1.0
    return out


class ShardedDataset:
    """A partitioned ``(features, labels)`` dataset — the "RDD".

    Parameters
    ----------
    features, labels:
        numpy arrays with matching leading dimension. ``labels`` may be
        ``None`` for inference-only datasets.
    num_partitions:
        number of logical worker shards (reference: RDD partitions).
    """

    def __init__(
        self,
        features: np.ndarray,
        labels: Optional[np.ndarray] = None,
        num_partitions: int = 1,
    ):
        features = np.asarray(features)
        if labels is not None:
            labels = np.asarray(labels)
            if len(labels) != len(features):
                raise ValueError(
                    f"features/labels length mismatch: {len(features)} vs {len(labels)}"
                )
        if num_partitions < 1:
            raise ValueError("num_partitions must be >= 1")
        if len(features) < num_partitions:
            raise ValueError(
                f"cannot split {len(features)} rows into {num_partitions} partitions"
            )
        self.features = features
        self.labels = labels
        self.num_partitions = int(num_partitions)
        # Contiguous equal-ish split, like Spark's default range partitioning
        # of a parallelized collection.
        self._bounds = np.linspace(0, len(features), self.num_partitions + 1).astype(int)

    # -- reference RDD surface -------------------------------------------------

    def __len__(self) -> int:
        return len(self.features)

    def count(self) -> int:
        return len(self)

    def getNumPartitions(self) -> int:  # noqa: N802 (Spark camelCase parity)
        return self.num_partitions

    def repartition(self, num_partitions: int) -> "ShardedDataset":
        """Return a new dataset with a different shard count (cheap: no copy)."""
        return ShardedDataset(self.features, self.labels, num_partitions)

    def partition(self, index: int) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """The ``(features, labels)`` slice owned by worker ``index``."""
        lo, hi = self._bounds[index], self._bounds[index + 1]
        labels = None if self.labels is None else self.labels[lo:hi]
        return self.features[lo:hi], labels

    def partitions(self) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray]]]:
        for i in range(self.num_partitions):
            yield self.partition(i)

    def partition_sizes(self) -> Sequence[int]:
        return list(np.diff(self._bounds))

    def shuffle(self, seed: int = 0) -> "ShardedDataset":
        """Globally permute rows (new dataset, same partitioning)."""
        from elephas_tpu.native import gather_rows

        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(self.features))
        features, labels = gather_rows(self.features, self.labels, perm)
        return ShardedDataset(features, labels, self.num_partitions)

    def take(self, n: int):
        if self.labels is None:
            return self.features[:n]
        return list(zip(self.features[:n], self.labels[:n]))

    # -- TPU-native surface ----------------------------------------------------

    def even_shards(self, n_shards: int) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Truncate to a multiple of ``n_shards`` and return stackable arrays.

        Used to build globally-sharded ``jax.Array`` batches: XLA requires
        equal shard sizes along the sharded axis, whereas Spark tolerates
        ragged partitions. Truncation (< n_shards rows) matches the
        reference's effective behavior of dropping remainder batches.
        """
        usable = (len(self.features) // n_shards) * n_shards
        labels = None if self.labels is None else self.labels[:usable]
        return self.features[:usable], labels


def to_simple_rdd(
    sc,
    features: np.ndarray,
    labels: np.ndarray,
    num_partitions: Optional[int] = None,
) -> ShardedDataset:
    """Build a ShardedDataset from arrays (reference ``to_simple_rdd``).

    ``sc`` (SparkContext in the reference) is accepted for signature parity
    and ignored — there is no Spark driver on a TPU pod.
    """
    del sc
    if num_partitions is None:
        num_partitions = 1
    return ShardedDataset(features, labels, num_partitions)


def to_labeled_point(
    sc,
    features: np.ndarray,
    labels: np.ndarray,
    categorical: bool = False,
) -> list:
    """Arrays -> list of LabeledPoint (reference ``to_labeled_point``).

    With ``categorical=True`` the labels are one-hot rows and the point
    label is the argmax class index, mirroring the reference.
    """
    del sc
    points = []
    for x, y in zip(features, labels):
        label = float(np.argmax(y)) if categorical else float(np.squeeze(y))
        points.append(LabeledPoint(label, np.asarray(x)))
    return points


def from_labeled_point(
    lp_list,
    categorical: bool = False,
    nb_classes: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """List of LabeledPoint -> (features, labels) arrays."""
    features = np.stack([np.asarray(lp.features) for lp in lp_list])
    if categorical:
        from elephas_tpu.native import encode_onehot

        if nb_classes is None:
            nb_classes = int(max(lp.label for lp in lp_list)) + 1
        int_labels = np.array([lp.label for lp in lp_list], dtype=np.int64)
        if int_labels.size and (int_labels.min() < 0 or int_labels.max() >= nb_classes):
            raise ValueError(
                f"labels outside [0, {nb_classes}): "
                f"min={int_labels.min()}, max={int_labels.max()}"
            )
        labels = encode_onehot(int_labels, nb_classes)
    else:
        labels = np.array([lp.label for lp in lp_list], dtype=np.float32)
    return features, labels


def lp_to_simple_rdd(
    lp_list,
    categorical: bool = False,
    nb_classes: Optional[int] = None,
    num_partitions: int = 1,
) -> ShardedDataset:
    """LabeledPoints -> ShardedDataset (reference ``lp_to_simple_rdd``)."""
    features, labels = from_labeled_point(lp_list, categorical, nb_classes)
    return ShardedDataset(features, labels, num_partitions)
