"""Data layer: device-sharded datasets, columnar DataFrame, adapters.

Replaces the reference's Spark RDD/DataFrame ingestion (SURVEY.md §2.1:
``elephas/utils/rdd_utils.py``, ``elephas/ml/adapter.py``,
``elephas/mllib/adapter.py``).
"""

from elephas_tpu.data.rdd import (  # noqa: F401
    LabeledPoint,
    ShardedDataset,
    encode_label,
    from_labeled_point,
    lp_to_simple_rdd,
    to_labeled_point,
    to_simple_rdd,
)
from elephas_tpu.data.dataframe import DataFrame  # noqa: F401
from elephas_tpu.data import datasets  # noqa: F401
