"""Benchmark dataset loaders: local cache or deterministic synthetic.

The reference's examples/benchmarks train on MNIST, CIFAR-10, and IMDB
(BASELINE.md eval configs; reference ``examples/*.py``). This environment
has no network egress, so each loader resolves in order:

1. A local file under ``$ELEPHAS_DATA_DIR`` (default
   ``~/.elephas_tpu/data``) in the standard Keras archive format —
   drop-in locations:

   - ``mnist.npz``    — arrays ``x_train,y_train,x_test,y_test``
     (uint8 images ``(N,28,28)``, integer labels)
   - ``cifar10.npz``  — same keys, ``(N,32,32,3)`` uint8 — or the
     original ``cifar-10-batches-py/`` pickle directory
   - ``imdb.npz``     — object arrays of int sequences + binary labels

2. A deterministic synthetic stand-in with identical shapes/dtypes and
   enough class structure to be learnable, so every pipeline runs (and
   converges) end-to-end without the real data. Loaders return
   ``real=False`` in that case and the parity harness labels its output
   accordingly — synthetic accuracy is NOT comparable to published
   MNIST/CIFAR numbers.
"""

from __future__ import annotations

import os
import pickle
from typing import Tuple

import numpy as np

Arrays = Tuple[np.ndarray, np.ndarray]


def data_dir() -> str:
    return os.environ.get(
        "ELEPHAS_DATA_DIR", os.path.join(os.path.expanduser("~"), ".elephas_tpu", "data")
    )


def _npz(path: str):
    with np.load(path, allow_pickle=True) as f:
        return (f["x_train"], f["y_train"]), (f["x_test"], f["y_test"])


# ---------------------------------------------------------------- MNIST


def synthetic_mnist(n_train: int = 8192, n_test: int = 2048, seed: int = 7):
    """Class-prototype images + noise, uint8 (N,28,28).

    Difficulty is CALIBRATED, not maximal: classes share a common base
    pattern and differ only through a damped class-specific component, so
    the parity configs land in a discriminative val-acc band (~0.7–0.9)
    instead of saturating at 1.0 — a harness whose tasks saturate cannot
    detect a mode that converges worse (VERDICT r2 weak #2).
    """
    rng = np.random.default_rng(seed)
    base = rng.random((28, 28)).astype(np.float32)
    protos = np.clip(
        base[None] + 0.33 * rng.normal(size=(10, 28, 28)).astype(np.float32), 0, 1
    )
    out = []
    for n, s in ((n_train, 0), (n_test, 1)):
        r = np.random.default_rng(seed + 1000 + s)
        labels = r.integers(0, 10, size=n)
        imgs = protos[labels] * 200.0 * (0.6 + 0.4 * r.random((n, 1, 1)))
        imgs = imgs + r.normal(scale=60.0, size=(n, 28, 28))
        # ~12% label noise (train AND test): bounds the Bayes-optimal
        # val_acc near 0.89 so healthy runs land in a band that can
        # still rank coordination modes instead of pinning at 1.0.
        flip = r.random(n) < 0.12
        labels = np.where(flip, r.integers(0, 10, size=n), labels)
        out.append((np.clip(imgs, 0, 255).astype(np.uint8), labels.astype(np.int64)))
    return out[0], out[1]


def load_mnist():
    """Returns ``((x_train, y_train), (x_test, y_test), real)``; images
    uint8 (N,28,28), labels int."""
    path = os.path.join(data_dir(), "mnist.npz")
    if os.path.exists(path):
        train, test = _npz(path)
        return train, test, True
    train, test = synthetic_mnist()
    return train, test, False


# ---------------------------------------------------------------- CIFAR-10


def synthetic_cifar10(n_train: int = 50000, n_test: int = 10000, seed: int = 11):
    """Low-frequency colored class patterns + noise, uint8 (N,32,32,3).

    Defaults match the real CIFAR-10 split sizes so throughput/epoch
    economics in the parity harness are comparable to the real dataset.
    """
    rng = np.random.default_rng(seed)
    grid = np.stack(np.meshgrid(np.linspace(0, 1, 32), np.linspace(0, 1, 32)), -1)
    protos = np.zeros((10, 32, 32, 3), np.float32)
    for c in range(10):
        fx, fy = rng.uniform(1, 4, 2)
        phase = rng.uniform(0, 2 * np.pi, 3)
        for ch in range(3):
            protos[c, :, :, ch] = 0.5 + 0.5 * np.sin(
                2 * np.pi * (fx * grid[..., 0] + fy * grid[..., 1]) + phase[ch]
            )
    out = []
    for n, s in ((n_train, 0), (n_test, 1)):
        r = np.random.default_rng(seed + 1000 + s)
        labels = r.integers(0, 10, size=n)
        # Amplitude jitter + noise + ~12% label noise calibrated for a
        # discriminative band (healthy runs ~0.6–0.9, not 1.0) — a ResNet
        # separates the clean patterns perfectly given enough epochs, so
        # the label noise bounds Bayes-optimal val_acc near 0.89
        # (VERDICT r2 weak #2).
        imgs = protos[labels] * 255.0 * (0.7 + 0.3 * r.random((n, 1, 1, 1)))
        imgs = imgs + r.normal(scale=34.0, size=imgs.shape)
        flip = r.random(n) < 0.12
        labels = np.where(flip, r.integers(0, 10, size=n), labels)
        out.append((np.clip(imgs, 0, 255).astype(np.uint8), labels.astype(np.int64)))
    return out[0], out[1]


def load_cifar10():
    """Returns ``((x_train, y_train), (x_test, y_test), real)``; images
    uint8 (N,32,32,3), labels int."""
    path = os.path.join(data_dir(), "cifar10.npz")
    if os.path.exists(path):
        train, test = _npz(path)
        return train, test, True
    batch_dir = os.path.join(data_dir(), "cifar-10-batches-py")
    if os.path.isdir(batch_dir):
        xs, ys = [], []
        for name in [f"data_batch_{i}" for i in range(1, 6)]:
            with open(os.path.join(batch_dir, name), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.append(d[b"labels"])
        x_train = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        y_train = np.concatenate(ys).astype(np.int64)
        with open(os.path.join(batch_dir, "test_batch"), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x_test = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        y_test = np.asarray(d[b"labels"], dtype=np.int64)
        return (x_train, y_train), (x_test, y_test), True
    train, test = synthetic_cifar10()
    return train, test, False


# ---------------------------------------------------------------- IMDB


def synthetic_imdb(
    n_train: int = 8192,
    n_test: int = 2048,
    num_words: int = 20000,
    maxlen: int = 200,
    seed: int = 13,
):
    """Two token 'topics' over the vocab; sequences already padded."""
    rng = np.random.default_rng(seed)
    # Class-conditional word distributions sharing a common core.
    base = rng.dirichlet(np.full(num_words, 0.05))
    tilt = rng.normal(size=num_words)
    # Mild tilt: strongly class-tilted vocabularies saturate val_acc at
    # 1.0 within one epoch (VERDICT r2 weak #2); 0.58 keeps the task
    # learnable but discriminative (~0.75–0.9 for a healthy LSTM).
    pos = base * np.exp(0.58 * tilt)
    neg = base * np.exp(-0.58 * tilt)
    pos, neg = pos / pos.sum(), neg / neg.sum()
    out = []
    for n, s in ((n_train, 0), (n_test, 1)):
        r = np.random.default_rng(seed + 1000 + s)
        labels = r.integers(0, 2, size=n)
        lengths = r.integers(maxlen // 4, maxlen, size=n)
        x = np.zeros((n, maxlen), dtype=np.int32)
        for i in range(n):
            dist = pos if labels[i] else neg
            toks = r.choice(num_words, size=lengths[i], p=dist)
            x[i, -lengths[i]:] = toks  # Keras-style pre-padding with 0
        # ~12% label noise (both splits): once the embedding aligns, the
        # topic signal is fully separable and val_acc snaps to 1.0 — the
        # noise bounds a healthy full run near 0.88 (VERDICT r2 weak #2).
        flip = r.random(n) < 0.12
        labels = np.where(flip, 1 - labels, labels)
        out.append((x, labels.astype(np.int64)))
    return out[0], out[1]


def _pad_sequences(seqs, maxlen: int) -> np.ndarray:
    x = np.zeros((len(seqs), maxlen), dtype=np.int32)
    for i, s in enumerate(seqs):
        s = np.asarray(s, dtype=np.int32)[-maxlen:]
        x[i, maxlen - len(s):] = s
    return x


def load_imdb(num_words: int = 20000, maxlen: int = 200):
    """Returns ``((x_train, y_train), (x_test, y_test), real)``; padded
    int32 token matrices (N, maxlen), binary labels."""
    path = os.path.join(data_dir(), "imdb.npz")
    if os.path.exists(path):
        (xtr, ytr), (xte, yte) = _npz(path)
        xtr = _pad_sequences([np.minimum(s, num_words - 1) for s in xtr], maxlen)
        xte = _pad_sequences([np.minimum(s, num_words - 1) for s in xte], maxlen)
        return (xtr, ytr.astype(np.int64)), (xte, yte.astype(np.int64)), True
    train, test = synthetic_imdb(num_words=num_words, maxlen=maxlen)
    return train, test, False


def one_hot(labels: np.ndarray, num_classes: int) -> np.ndarray:
    return np.eye(num_classes, dtype=np.float32)[np.asarray(labels, dtype=np.int64)]
