"""elephas_tpu — a TPU-native distributed deep-learning framework.

A from-scratch rebuild of the capabilities of ``marcoleewow/elephas``
(Spark-distributed Keras training; see SURVEY.md) on JAX/XLA for TPU:

- Spark executors          -> TPU devices in a ``jax.sharding.Mesh``
- TF-CPU per-worker compute-> per-chip ``jax.jit`` train steps
- Flask/socket param server-> ICI allreduce (``lax.psum``) for synchronous
                              data parallelism; an HBM-resident parameter
                              buffer (+ optional HTTP/socket transports for
                              cross-host control plane) for asynchronous /
                              hogwild (Downpour SGD) modes
- Spark RDDs               -> ``ShardedDataset`` (device-sharded numpy)
- Spark-ML Pipeline stages -> columnar ``DataFrame`` + Estimator/Transformer
- Hyperas/hyperopt search  -> device-parallel independent trials

Driver-side API parity targets (reference symbols, SURVEY.md §2.1):
``elephas/spark_model.py::SparkModel``, ``elephas/ml_model.py::
ElephasEstimator``, ``elephas/hyperparam.py::HyperParamModel``.
(The reference mount was empty at build time; citations are given as
``file::Symbol`` per SURVEY.md's provenance note.)
"""

__version__ = "0.1.0"

from elephas_tpu.api.spark_model import (  # noqa: F401
    SparkModel,
    SparkMLlibModel,
    TpuModel,
    load_spark_model,
)
from elephas_tpu.api.compile import CompiledModel, compile_model  # noqa: F401
from elephas_tpu.serialize.keras_bridge import from_keras  # noqa: F401
from elephas_tpu.data.rdd import ShardedDataset, to_simple_rdd  # noqa: F401
from elephas_tpu.data.dataframe import DataFrame  # noqa: F401
from elephas_tpu.ml import ElephasEstimator, ElephasTransformer  # noqa: F401
from elephas_tpu.hyperparam import HyperParamModel, hp  # noqa: F401
from elephas_tpu.serving import InferenceEngine  # noqa: F401
