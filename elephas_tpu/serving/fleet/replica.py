"""One serving replica: an ``InferenceEngine`` dressed as a process.

The fleet's unit of actuation is not the engine but the *slot* it runs
in: something that can be spawned, drained, killed mid-traffic, and
restarted as a new boot of the same name — exactly the lifecycle the
``FleetAggregator`` already narrates for training processes
(alive → stale → dead → alive, boot counter bumped). ``Replica`` wraps
an engine with that lifecycle plus the three per-replica signal feeds
the router dispatches on:

- ``load_score()`` / ``queue_frac()`` — the saturation plane, read
  straight off ``engine.load`` / ``engine.queue`` (N replicas share one
  process-global metrics registry, so the published
  ``serving_load_score`` gauge would be whichever replica wrote last —
  the router must read the trackers, not the gauges),
- ``worst_burn()`` — worst-objective multi-window goodput burn from the
  replica's own ledger,
- ``shedding`` — a latched per-replica burn alert: an ``AlertEngine``
  evaluated against ``_BurnMetricsView`` (this replica's burn family
  only) with the stock ``goodput_burn_*`` rules, so shed/unshed
  inherits the alert plane's latch-until-clean semantics instead of
  re-inventing flap suppression in the router.

Death comes in two flavors and the distinction is load-bearing for the
router's recovery path: a *drain* (``drain()`` → ``maybe_finish_drain()``)
finishes and hands out every routed request before the serve thread
stops (``drained=True``), while a *kill* halts the engine mid-step —
frozen requests surface to waiting callers as ``ReplicaDead``, the
router's cue to resubmit them elsewhere.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from elephas_tpu import obs
from elephas_tpu.obs.alerts import AlertEngine, default_rules
from elephas_tpu.obs.canary import CanaryDriver
from elephas_tpu.utils import locksan

__all__ = ["DEAD", "DRAINING", "LIFECYCLES", "Replica", "ReplicaDead",
           "SERVING", "TIERS"]

SERVING = "serving"
DRAINING = "draining"
DEAD = "dead"

#: Serving tiers. ``mono`` replicas run the classic prefill+decode
#: loop; a disaggregated fleet splits them — ``prefill`` replicas stop
#: at the prompt and export KV handoffs, ``decode`` replicas import
#: those handoffs and run the token loop. Every tier is the SAME
#: engine; the tier only changes which requests the router sends it.
TIERS = ("mono", "prefill", "decode")

#: Replica lifecycle states, in the order a drain walks them.
LIFECYCLES = (SERVING, DRAINING, DEAD)

#: How often a blocked ``result()`` re-checks the replica's pulse — a
#: kill mid-wait surfaces as ``ReplicaDead`` within one slice instead
#: of blocking out the caller's full timeout.
RESULT_SLICE_S = 0.05

#: Default blackbox probe timeout for the per-replica canary. Shorter
#: than the canary module's 30s default: a fleet tick probes replicas
#: inline, and a wedged replica should cost one bounded slice of the
#: tick, not half a minute.
CANARY_TIMEOUT_S = 5.0


class ReplicaDead(RuntimeError):
    """The replica died un-drained while a routed request was still
    unfinished — the router's requeue trigger."""

    def __init__(self, replica_id: str, req_id: Optional[int] = None):
        super().__init__(f"replica {replica_id} is dead (req={req_id})")
        self.replica_id = replica_id
        self.req_id = req_id


class _BurnMetricsView:
    """Per-replica registry view for the burn ``AlertEngine``.

    ``snapshot()`` exposes only THIS replica's ledger-derived
    ``serving_goodput_burn{objective=,replica=}`` family (the
    process-global gauge mixes N replicas into one sample), while
    ``counter()`` delegates to the real default registry so
    ``alerts_fired_total`` still aggregates fleet-wide.
    """

    def __init__(self, replica: "Replica"):
        self._replica = replica

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        engine = self._replica.engine
        if engine is None:
            return out
        for name, burn in engine.slo.burn().items():
            if burn is not None:
                key = (f'serving_goodput_burn{{objective="{name}",'
                       f'replica="{self._replica.replica_id}"}}')
                out[key] = burn
        return out

    def counter(self, *args, **kwargs):
        return obs.default_registry().counter(*args, **kwargs)


class Replica:
    """One engine slot in the fleet, with a process-like lifecycle.

    ``engine_factory`` builds a fresh ``InferenceEngine`` per boot —
    a restart must come back with empty queues and a clean ledger, the
    way a real process restart does, so the replica cannot reuse a
    halted engine object.
    """

    def __init__(self, replica_id: str, engine_factory: Callable[[], Any],
                 *, clock: Callable[[], float] = time.monotonic,
                 mount_ops: bool = False,
                 store_dir: Optional[str] = None,
                 canary_timeout_s: float = CANARY_TIMEOUT_S,
                 tier: str = "mono"):
        if tier not in TIERS:
            raise ValueError(f"unknown tier {tier!r}; expected one of {TIERS}")
        self.replica_id = replica_id
        #: Which traffic the router sends here (see ``TIERS``). Fixed
        #: for the slot's lifetime — a re-tiering is a new slot.
        self.tier = tier
        self.engine_factory = engine_factory
        self.clock = clock
        self.mount_ops = mount_ops
        # Durable telemetry directory for every boot of this slot: each
        # respawn reopens it under a fresh store boot id, which is
        # exactly the cross-boot story the incident builder stitches.
        self.store_dir = store_dir
        self.canary_timeout_s = canary_timeout_s

        self.engine = None
        self.canary: Optional[CanaryDriver] = None
        self.state = DEAD
        self.boot = 0
        #: True only when the last shutdown was a completed drain —
        #: every routed result was claimed; nothing needs requeueing.
        self.drained = False
        #: Router bookkeeping: canary-flagged drains restart when the
        #: drain completes; autoscaler drains stay down.
        self.pending_restart = False
        #: Set by the ``RolloutController`` while this slot is the
        #: weight-rollout canary (bake in progress) — fleet_top stars
        #: the VERSION cell. Distinct from ``CanaryDriver`` (request
        #: probing) above.
        self.rollout_canary = False
        self.scale_down = False
        #: Canary failure count already acted on (drain-and-restart
        #: fires on *fresh* failures, not the lifetime total).
        self.seen_canary_failures = 0
        #: Latched burn-alert state, refreshed by ``evaluate_alerts()``
        #: (a plain attribute so the router's dispatch loop reads a
        #: stable value between ticks).
        self.shedding = False

        self.in_flight = 0
        self._lock = locksan.make_lock("Replica._lock")
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        self._alerts: Optional[AlertEngine] = None

    # -- lifecycle ---------------------------------------------------------

    def spawn(self) -> "Replica":
        """Boot: fresh engine, serve thread, canary, burn alerts, and
        (optionally) an ops endpoint on an ephemeral port."""
        if self.state != DEAD:
            raise RuntimeError(
                f"replica {self.replica_id} is {self.state}; "
                f"only a dead replica can spawn"
            )
        self.engine = self.engine_factory()
        self.boot += 1
        self.drained = False
        self.pending_restart = False
        self.scale_down = False
        self.seen_canary_failures = 0
        self.shedding = False
        with self._lock:
            self.in_flight = 0
        self.canary = CanaryDriver(self.engine,
                                   timeout_s=self.canary_timeout_s)
        self._alerts = AlertEngine(
            registry=_BurnMetricsView(self),
            rules=[r for r in default_rules()
                   if r.name.startswith("goodput_burn")],
            clock=self.clock,
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self.engine.serve_forever, args=(self._stop,),
            name=f"replica:{self.replica_id}", daemon=True)
        self._thread.start()
        if self.mount_ops:
            self.engine.mount_ops(port=0, store_dir=self.store_dir)
        self.state = SERVING
        return self

    def drain(self, *, reason: str = "operator") -> None:
        """Stop taking new work; finish what's routed here. The serve
        thread keeps stepping until ``maybe_finish_drain()`` observes
        an idle engine with every routed result claimed."""
        if self.state != SERVING:
            return
        self.state = DRAINING
        obs.default_flight_recorder().note(
            "replica_drain", "info", replica=self.replica_id,
            boot=self.boot, reason=reason)

    def maybe_finish_drain(self) -> bool:
        """Complete a drain once the engine is idle and all routed
        results were claimed. Returns True when the drain closed."""
        if self.state != DRAINING:
            return False
        with self._lock:
            busy = self.in_flight
        if busy or self.engine.scheduler.has_work:
            return False
        self._stop_serving()
        self.drained = True
        self.state = DEAD
        return True

    def kill(self) -> None:
        """Hard death mid-traffic: the engine halts wherever it was,
        the serve thread exits, the ops endpoint goes dark — the fleet
        aggregator must watch this replica *die*, not vanish. Frozen
        requests surface to waiting callers as ``ReplicaDead``."""
        if self.state == DEAD:
            return
        self.engine.halt()
        self._stop_serving(reason="kill")
        self.drained = False
        self.state = DEAD

    def restart(self, *, reason: str = "operator") -> "Replica":
        """Boot a dead replica again: same name, next boot number, a
        completely fresh engine."""
        if self.state != DEAD:
            raise RuntimeError(
                f"replica {self.replica_id} is {self.state}; "
                f"only a dead replica can restart"
            )
        self.spawn()
        obs.default_flight_recorder().note(
            "replica_restart", "info", replica=self.replica_id,
            boot=self.boot, reason=reason)
        return self

    def _stop_serving(self, reason: str = "close") -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self.engine is not None and self.engine.ops is not None:
            self.engine.unmount_ops(reason=reason)

    # -- router bookkeeping ------------------------------------------------

    def note_dispatch(self) -> None:
        with self._lock:
            self.in_flight += 1

    def note_done(self) -> None:
        with self._lock:
            self.in_flight -= 1

    def result(self, req_id: int, timeout_s: Optional[float] = None):
        """Claim a routed result, staying alert to death.

        The engine wait is sliced (``RESULT_SLICE_S``) so a kill
        mid-wait surfaces promptly. A killed replica gets one last
        zero-timeout claim — results the engine published before dying
        are still readable, like a dead process's output pipe — before
        the loss is declared as ``ReplicaDead``.
        """
        deadline = None if timeout_s is None else self.clock() + timeout_s
        while True:
            if self.state == DEAD and not self.drained:
                try:
                    return self.engine.result(req_id, timeout_s=0.0)
                except TimeoutError:
                    raise ReplicaDead(self.replica_id, req_id) from None
            try:
                return self.engine.result(req_id, timeout_s=RESULT_SLICE_S)
            except TimeoutError:
                if deadline is not None and self.clock() >= deadline:
                    raise

    def handoff(self, req_id: int, timeout_s: Optional[float] = None):
        """Claim a prefill-tier KV handoff, staying alert to death —
        the same sliced-wait / last-claim / ``ReplicaDead`` contract as
        ``result()``. Returns the handoff dict, or a
        ``GenerationResult`` when the request terminated locally
        (deadline eviction mid-prefill)."""
        deadline = None if timeout_s is None else self.clock() + timeout_s
        while True:
            if self.state == DEAD and not self.drained:
                try:
                    return self.engine.handoff(req_id, timeout_s=0.0)
                except TimeoutError:
                    raise ReplicaDead(self.replica_id, req_id) from None
            try:
                return self.engine.handoff(req_id,
                                           timeout_s=RESULT_SLICE_S)
            except TimeoutError:
                if deadline is not None and self.clock() >= deadline:
                    raise

    # -- signals -----------------------------------------------------------

    def load_score(self) -> float:
        """Composite saturation score from the replica's own tracker
        (never the process-global gauge — see module docstring)."""
        score = self.engine.load.snapshot()["score"]
        return 0.0 if score is None else score

    def queue_frac(self) -> float:
        """Admission-queue fullness in [0, 1]."""
        return len(self.engine.queue) / self.engine.queue.max_depth

    def kv_pressure(self) -> float:
        """Fraction of the paged KV pool in use, in [0, 1] (0.0 when
        the engine has no paged pool). The decode-tier dispatch signal:
        a decode replica out of free blocks cannot import a handoff
        without evicting prefix state first."""
        sig = self.engine.load.snapshot().get("signals") or {}
        free = sig.get("kv_free_frac")
        return 0.0 if free is None else max(0.0, 1.0 - free)

    def worst_burn(self) -> float:
        """Worst-objective multi-window burn (0.0 before any traffic)
        — the autoscaler's per-replica input."""
        if self.engine is None:
            return 0.0
        burns = [b for b in self.engine.slo.burn().values()
                 if b is not None]
        return max(burns) if burns else 0.0

    def evaluate_alerts(self, now: Optional[float] = None) -> None:
        """Re-evaluate the latched per-replica burn alerts and refresh
        ``shedding`` (called from the router's ``tick()``)."""
        if self._alerts is None or self.state != SERVING:
            return
        self._alerts.evaluate(now)
        self.shedding = bool(self._alerts.snapshot()["active"])

    def signals(self) -> Dict[str, Any]:
        """JSON-ready signal card for the router's ``/replicas`` doc
        and ``fleet_top``'s replica board."""
        doc: Dict[str, Any] = {
            "state": self.state,
            "tier": self.tier,
            "boot": self.boot,
            "drained": self.drained,
            "in_flight": self.in_flight,
            "load_score": None,
            "queue_depth": None,
            "queue_frac": None,
            "burn_worst": None,
            "kv_pressure": None,
            "shedding": False,
            "canary_probes": 0,
            "canary_failures": 0,
            "ops_port": None,
            "model_version": None,
            "rollout_canary": self.rollout_canary,
        }
        if self.engine is None:
            return doc
        doc["model_version"] = self.engine.model_version
        if self.state != DEAD:
            doc["load_score"] = self.load_score()
            doc["queue_depth"] = len(self.engine.queue)
            doc["queue_frac"] = self.queue_frac()
            doc["burn_worst"] = self.worst_burn()
            doc["shedding"] = self.shedding
            doc["kv_pressure"] = self.kv_pressure()
        if self.canary is not None:
            doc["canary_probes"] = self.canary.probes
            doc["canary_failures"] = self.canary.failures
        if self.engine.ops is not None:
            doc["ops_port"] = self.engine.ops.port
        return doc
