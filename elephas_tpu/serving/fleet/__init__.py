"""Replicated serving fleet: N ``InferenceEngine`` replicas behind a
signal-driven router that *actuates* (ROADMAP: the first real
observe→decide→act loop).

Five PRs of telemetry — load scores, goodput burn, latched alerts,
blackbox canaries, fleet federation — observed a single engine; this
package is where those signals finally steer traffic:

- ``Replica``       — one engine plus the process-like trimmings the
                      fleet needs: a serve thread, a lifecycle
                      (serving → draining → dead), an optional ops
                      endpoint the ``FleetAggregator`` polls, a
                      blackbox ``CanaryDriver``, and a per-replica
                      latched burn-alert view (``serving.fleet.replica``),
- ``ReplicaSet``    — the roster: spawn / drain / kill / restart by id,
                      each boot numbered so a restart is visibly the
                      same slot coming back different
                      (``serving.fleet.replica_set``),
- ``Router``        — the client-facing submit/result surface (the
                      scheduler/engine seam from the serving PR, one
                      level up: router fronts engines the way the
                      scheduler fronts slots). Dispatch is ranked by
                      per-replica ``serving_load_score``, queue
                      pressure, and goodput burn; session-affinity
                      keeps follow-up turns on the replica holding the
                      KV state (explicit ``affinity_miss_total`` when
                      it can't); ``tick()`` sheds latched-burn
                      replicas, drain-and-restarts canary-flagged
                      ones, requeues in-flight work off dead replicas,
                      and actuates the autoscaler
                      (``serving.fleet.router``),
- ``FleetAutoscaler`` — replica-count decisions from the multi-window
                      burn rate: hysteresis dead band, consecutive-
                      observation streaks, cooldown, every decision a
                      ``fleet_scale`` flight event and a
                      ``fleet_scale_events_total{direction=}`` tick
                      (``serving.fleet.autoscaler``).

Proof obligations carried by tests + ``lm_bench.py --fleet``: a
single-replica routed fleet is token-identical to a bare engine; a
replica killed mid-traffic costs a bounded (canary-observed) blackbox
outage and a bounded real-goodput dip while every in-flight request
completes via requeue; the autoscaler's decision sequence under a
seeded burst replays exactly.
"""

from elephas_tpu.serving.fleet.autoscaler import FleetAutoscaler  # noqa: F401
from elephas_tpu.serving.fleet.qos import (  # noqa: F401
    AdmissionThrottled,
    QoSPolicy,
    TokenBucket,
)
from elephas_tpu.serving.fleet.replica import (  # noqa: F401
    DEAD,
    DRAINING,
    LIFECYCLES,
    TIERS,
    Replica,
    ReplicaDead,
    SERVING,
)
from elephas_tpu.serving.fleet.replica_set import ReplicaSet  # noqa: F401
from elephas_tpu.serving.fleet.router import (  # noqa: F401
    FleetUnavailable,
    Router,
)
