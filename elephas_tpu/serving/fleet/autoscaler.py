"""Goodput-burn-driven replica-count decisions.

The autoscaler is the fleet's slowest control loop, so it is built as
a *pure decision core*: ``observe(burn=, n_replicas=, now=)`` folds one
observation into streak state and returns ``"up"``, ``"down"``, or
``None``. No threads, no sleeps, no wall-clock reads outside the
injectable ``clock`` — the same observation sequence always yields the
same decision sequence, which is what lets tests pin the replay and
the chaos bench gate on it.

Three stabilizers keep it from flapping, mirroring the alert plane's
latch-until-clean philosophy one level up:

- a **hysteresis dead band** between ``down_burn`` and ``up_burn``
  where streaks reset — burn hovering near a single threshold can't
  oscillate the fleet,
- **consecutive-observation streaks** (``up_after``/``down_after``):
  one bad window is a blip; N in a row is a trend. Scaling down
  demands a longer streak than scaling up, because under-capacity
  burns SLO budget while over-capacity only burns money,
- a **cooldown** after every actuation, long enough for the
  multi-window burn to actually reflect the new capacity before the
  next decision (reacting to a signal that hasn't seen the last action
  yet is how autoscalers pump).

Every decision is narrated twice: a ``fleet_scale`` flight event (the
post-mortem surface) and a ``fleet_scale_events_total{direction=}``
counter tick (the dashboard surface).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from elephas_tpu import obs

__all__ = ["FleetAutoscaler"]

#: How many recent decisions the snapshot carries (the full list stays
#: on the instance for tests; the ops doc stays bounded).
SNAPSHOT_DECISIONS = 32


class FleetAutoscaler:
    """Replica-count policy from multi-window goodput burn."""

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 8,
                 up_burn: float = 1.0, down_burn: float = 0.25,
                 up_after: int = 2, down_after: int = 3,
                 cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= "
                f"min_replicas ({min_replicas})")
        if not down_burn < up_burn:
            raise ValueError(
                f"need down_burn < up_burn for a hysteresis band, got "
                f"down={down_burn} up={up_burn}")
        if up_after < 1 or down_after < 1:
            raise ValueError(
                f"streak lengths must be >= 1, got up_after={up_after} "
                f"down_after={down_after}")
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.up_burn = up_burn
        self.down_burn = down_burn
        self.up_after = up_after
        self.down_after = down_after
        self.cooldown_s = cooldown_s
        self.clock = clock

        self.observations = 0
        self.decisions: List[Dict[str, Any]] = []
        self._up_streak = 0
        self._down_streak = 0
        self._last_scale_t: Optional[float] = None

    def observe(self, *, burn: float, n_replicas: int,
                now: Optional[float] = None) -> Optional[str]:
        """Fold one fleet-burn observation; maybe decide.

        Streaks advance even during cooldown (the trend is real either
        way), but actuation waits the cooldown out — the first
        observation after it expires can fire immediately if the
        streak held.
        """
        now = self.clock() if now is None else now
        self.observations += 1
        if burn > self.up_burn:
            self._up_streak += 1
            self._down_streak = 0
        elif burn < self.down_burn:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # Hysteresis dead band: neither trend survives it.
            self._up_streak = 0
            self._down_streak = 0

        cooling = (self._last_scale_t is not None
                   and now - self._last_scale_t < self.cooldown_s)
        direction = None
        if cooling:
            pass
        elif (self._up_streak >= self.up_after
                and n_replicas < self.max_replicas):
            direction = "up"
        elif (self._down_streak >= self.down_after
                and n_replicas > self.min_replicas):
            direction = "down"
        if direction is None:
            return None

        self._up_streak = 0
        self._down_streak = 0
        self._last_scale_t = now
        record = {"t": now, "direction": direction, "burn": burn,
                  "replicas": n_replicas}
        self.decisions.append(record)
        obs.default_flight_recorder().note(
            "fleet_scale", "info", direction=direction, burn=burn,
            replicas=n_replicas)
        obs.default_registry().counter(
            "fleet_scale_events_total",
            help="autoscaler decisions actuated, by direction",
            labelnames=("direction",),
        ).labels(direction=direction).inc()
        return direction

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready policy + recent-decision card for ``/replicas``."""
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "up_burn": self.up_burn,
            "down_burn": self.down_burn,
            "up_after": self.up_after,
            "down_after": self.down_after,
            "cooldown_s": self.cooldown_s,
            "observations": self.observations,
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
            "last_scale_t": self._last_scale_t,
            "decisions": list(self.decisions[-SNAPSHOT_DECISIONS:]),
            "last": self.decisions[-1] if self.decisions else None,
        }
