"""Multi-tenant QoS: token buckets, weighted fair share, priorities.

The router's admission layer, sitting ABOVE per-replica queue
admission. Three mechanisms, composed in ``try_admit``:

1. **Token buckets** (per tenant): sustained rate + burst, denominated
   in *work units* (prompt tokens + decode budget — the same unit the
   cost ledger bills). A drained bucket throttles the tenant with
   ``AdmissionThrottled`` (a ``QueueFull`` subclass, so
   ``submit_with_retry``-style clients back off unchanged) carrying a
   ``retry_after`` computed from the refill rate, and an
   ``admission_throttle`` flight for the ops plane.

2. **Weighted fair share** (stride scheduling): every admit advances
   the tenant's virtual time by ``cost / weight``. A tenant whose
   vtime runs more than ``fairness_window`` ahead of the
   slowest active tenant is throttled even with bucket credit — burst
   capacity cannot buy an unfair share of a contended fleet.

3. **Priority classes**: class 0 (interactive) bypasses the fairness
   window and may *preempt* — when every replica's queue rejects a
   class-0 submit, the router cancels one still-QUEUED lower-priority
   request (``tenant_preempted`` flight) and retries in the freed
   slot. Admitted (slot-holding) work is never clawed back; the victim
   redispatches under its own fair share.

The policy is pure bookkeeping — it holds no queue and runs no thread;
the router calls it synchronously on each submit, which keeps the
whole QoS plane deterministic under a fake clock.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

from elephas_tpu import obs
from elephas_tpu.serving.scheduler import QueueFull
from elephas_tpu.utils import locksan

__all__ = ["AdmissionThrottled", "QoSPolicy", "TokenBucket"]

#: Default sustained admission rate (work units / second) and burst
#: for tenants without an explicit bucket. Generous: QoS must be
#: invisible until someone configures it tighter.
DEFAULT_RATE = 1e6
DEFAULT_BURST = 1e6
#: Default fair-share window, in work units: how far one tenant's
#: weighted virtual time may run ahead of the slowest active tenant.
DEFAULT_FAIRNESS_WINDOW = 1e6
#: Priority class for tenants without an explicit one. Class 0 is
#: interactive (preempts); higher numbers yield earlier.
DEFAULT_PRIORITY = 1


class AdmissionThrottled(QueueFull):
    """QoS refused the submit (bucket drained or fair-share overdraft)
    — same retry contract as a replica's ``QueueFull``, so clients
    back off identically, but carries the tenant and reason so the
    caller can tell policy from capacity."""

    def __init__(self, tenant: str, reason: str, retry_after: float):
        RuntimeError.__init__(
            self,
            f"tenant {tenant!r} throttled ({reason}); retry after "
            f"{retry_after:.2f}s")
        self.tenant = tenant
        self.reason = reason
        self.retry_after = retry_after


class TokenBucket:
    """Classic leaky bucket in work units; caller supplies ``now``."""

    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float, burst: float, now: float = 0.0):
        if rate <= 0 or burst <= 0:
            raise ValueError(f"rate/burst must be > 0, got {rate}/{burst}")
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.last = now

    def refill(self, now: float) -> None:
        if now > self.last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self.last) * self.rate)
        self.last = now

    def try_take(self, cost: float, now: float) -> Optional[float]:
        """Drain ``cost`` if covered; else return seconds until it
        would be (the throttle's ``retry_after``)."""
        self.refill(now)
        if self.tokens >= cost:
            self.tokens -= cost
            return None
        return (cost - self.tokens) / self.rate


class _TenantState:
    __slots__ = ("bucket", "weight", "priority", "vtime",
                 "admitted", "throttled", "preempted")

    def __init__(self, bucket: TokenBucket, weight: float, priority: int):
        self.bucket = bucket
        self.weight = weight
        self.priority = priority
        self.vtime = 0.0
        self.admitted = 0
        self.throttled = 0
        self.preempted = 0


class QoSPolicy:
    """Per-tenant admission policy for the fleet router.

    ``buckets`` maps tenant -> (rate, burst); ``weights`` maps tenant
    -> fair-share weight (default 1.0); ``priorities`` maps tenant ->
    class (0 preempts). Unknown tenants get the permissive defaults,
    so only configured tenants feel the policy.
    """

    def __init__(self, *,
                 buckets: Optional[Dict[str, Tuple[float, float]]] = None,
                 weights: Optional[Dict[str, float]] = None,
                 priorities: Optional[Dict[str, int]] = None,
                 fairness_window: float = DEFAULT_FAIRNESS_WINDOW,
                 clock: Callable[[], float] = time.monotonic):
        self.fairness_window = fairness_window
        self.clock = clock
        self._buckets = dict(buckets or {})
        self._weights = dict(weights or {})
        self._priorities = dict(priorities or {})
        self._tenants: Dict[str, _TenantState] = {}
        self._lock = locksan.make_lock("QoSPolicy._lock")

    # -- tenant state ------------------------------------------------------

    def _state(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            rate, burst = self._buckets.get(
                tenant, (DEFAULT_RATE, DEFAULT_BURST))
            st = _TenantState(
                TokenBucket(rate, burst, self.clock()),
                self._weights.get(tenant, 1.0),
                self._priorities.get(tenant, DEFAULT_PRIORITY))
            # New tenants start at the fleet's current floor, not at
            # zero — joining late must not grant a huge vtime credit.
            if self._tenants:
                st.vtime = min(t.vtime for t in self._tenants.values())
            self._tenants[tenant] = st
        return st

    def priority(self, tenant: Optional[str]) -> int:
        if tenant is None:
            return DEFAULT_PRIORITY
        with self._lock:
            return self._state(tenant).priority

    # -- admission ---------------------------------------------------------

    def try_admit(self, tenant: Optional[str], cost: float) -> None:
        """Admit or raise ``AdmissionThrottled``. ``cost`` is in work
        units (prompt tokens + decode budget)."""
        if tenant is None:
            return  # untagged traffic bypasses QoS, like the ledger's
            # "default" tenant bypasses per-tenant budgets
        now = self.clock()
        with self._lock:
            st = self._state(tenant)
            floor = min(t.vtime for t in self._tenants.values())
            if (st.priority > 0
                    and st.vtime - floor > self.fairness_window):
                st.throttled += 1
                retry = (st.vtime - floor - self.fairness_window) \
                    / (st.bucket.rate * st.weight)
                err = AdmissionThrottled(tenant, "fair_share", retry)
            else:
                retry_after = st.bucket.try_take(cost, now)
                if retry_after is None:
                    st.admitted += 1
                    st.vtime += cost / st.weight
                    return
                st.throttled += 1
                err = AdmissionThrottled(tenant, "bucket", retry_after)
        obs.default_flight_recorder().note(
            "admission_throttle", "warn", tenant=tenant,
            reason=err.reason, cost=cost,
            retry_after=round(err.retry_after, 4))
        raise err

    def note_preempted(self, tenant: Optional[str]) -> None:
        """Bookkeeping when the router preempts this tenant's queued
        request (the router emits the ``tenant_preempted`` flight —
        it knows the victim/beneficiary pair; we only count)."""
        if tenant is None:
            return
        with self._lock:
            self._state(tenant).preempted += 1

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready per-tenant policy card for ``/tiers`` and
        ``fleet_top``'s QOS board."""
        now = self.clock()
        with self._lock:
            tenants: Dict[str, Any] = {}
            for name, st in sorted(self._tenants.items()):
                st.bucket.refill(now)
                tenants[name] = {
                    "bucket_fill": round(
                        st.bucket.tokens / st.bucket.burst, 4),
                    "rate": st.bucket.rate,
                    "burst": st.bucket.burst,
                    "weight": st.weight,
                    "priority": st.priority,
                    "vtime": round(st.vtime, 3),
                    "admitted": st.admitted,
                    "throttled": st.throttled,
                    "preempted": st.preempted,
                }
            return {
                "fairness_window": self.fairness_window,
                "tenants": tenants,
            }
