"""The fleet roster: named replica slots with explicit lifecycles.

A ``ReplicaSet`` owns N ``Replica`` slots and nothing else — no
dispatch policy, no signals interpretation; that's the router's job.
What it does own is *identity*: replica ids are assigned once
(``r0``, ``r1``, ...) and dead replicas stay in the roster, because
death is a state the fleet plane narrates (the aggregator's
alive → stale → dead → alive arcs need the slot to persist across the
outage), not an eviction. A restart is the same slot coming back with
the next boot number; a scale-up is a genuinely new slot.
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Any, Callable, Dict, List, Optional

from elephas_tpu.serving.fleet.replica import DEAD, SERVING, Replica

__all__ = ["ReplicaSet"]


class ReplicaSet:
    """Spawn / drain / kill / restart replicas by id.

    ``engine_factory`` is shared by every slot — each spawn builds a
    fresh engine, so replicas never share queues or ledgers (they *do*
    share compiled model state inside the factory's closure, which is
    what makes an in-process fleet cheap enough to bench).
    """

    def __init__(self, engine_factory: Callable[[], Any], *,
                 initial: int = 1,
                 tiers: Optional[Dict[str, int]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 mount_ops: bool = False,
                 store_root: Optional[str] = None):
        if tiers is None:
            if initial < 1:
                raise ValueError(f"initial must be >= 1, got {initial}")
        elif not tiers or any(n < 1 for n in tiers.values()):
            raise ValueError(f"tiers must map tier -> count >= 1, "
                             f"got {tiers}")
        self.engine_factory = engine_factory
        self.clock = clock
        self.mount_ops = mount_ops
        # One durable telemetry slot dir per replica id under this root
        # (requires mount_ops — the store mounts with the ops endpoint).
        self.store_root = store_root
        self._seq = itertools.count()
        self.replicas: Dict[str, Replica] = {}
        # A disaggregated roster spawns per-tier slots instead of
        # ``initial`` monoliths, e.g. tiers={"prefill": 1, "decode": 2}.
        if tiers is None:
            for _ in range(initial):
                self.spawn()
        else:
            for tier, count in tiers.items():
                for _ in range(count):
                    self.spawn(tier=tier)

    def spawn(self, tier: str = "mono") -> Replica:
        """Add a new slot to the roster and boot it."""
        rid = f"r{next(self._seq)}"
        store_dir = (os.path.join(self.store_root, rid, "telemetry")
                     if self.store_root else None)
        rep = Replica(rid, self.engine_factory, clock=self.clock,
                      mount_ops=self.mount_ops, store_dir=store_dir,
                      tier=tier)
        rep.spawn()
        self.replicas[rid] = rep
        return rep

    def get(self, replica_id: str) -> Replica:
        return self.replicas[replica_id]

    def __len__(self) -> int:
        return len(self.replicas)

    def serving(self, tier: Optional[str] = None) -> List[Replica]:
        """Replicas currently accepting new work, in id order —
        optionally only those of one tier."""
        return [r for r in self.replicas.values()
                if r.state == SERVING
                and (tier is None or r.tier == tier)]

    def drain(self, replica_id: str, *, reason: str = "operator") -> None:
        self.replicas[replica_id].drain(reason=reason)

    def kill(self, replica_id: str) -> Replica:
        rep = self.replicas[replica_id]
        rep.kill()
        return rep

    def restart(self, replica_id: str, *,
                reason: str = "operator") -> Replica:
        return self.replicas[replica_id].restart(reason=reason)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-replica signal cards, keyed by replica id."""
        return {rid: rep.signals() for rid, rep in self.replicas.items()}

    def close(self) -> None:
        """Teardown for benches/tests: hard-stop every live replica."""
        for rep in self.replicas.values():
            if rep.state != DEAD:
                rep.kill()
