"""Admission router: the fleet's client-facing submit/result surface.

The router reuses the scheduler/engine seam one level up — the same
``submit() -> id`` / ``result(id)`` contract the engine offers, fronted
over N replicas — so a client (and the benches, and the canary driver
pointed at the router) cannot tell a fleet from a bare engine except
by throughput. That contract is also the proof surface: with one
replica and no faults, routed output must be token-identical to a bare
engine's.

Dispatch is signal-driven, not round-robin. Each submit ranks the
serving replicas by a composite **dispatch cost** —

    load_score  (the saturation plane's smoothed composite)
  + w_q * queue_frac  (admission queue fullness)
  + w_b * burn        (worst-objective goodput burn, capped)

— with two overrides. **Session affinity**: a follow-up turn goes to
the replica already holding that session's KV state, whatever its
cost, because a re-prefill is pure waste; the pin breaks (and
``affinity_miss_total`` counts it, per session card) only when that
replica is draining, dead, or shedding. **Shed latch**: a replica
whose ``goodput_burn_high`` alert latched ranks behind every clean
replica and takes new work only when nothing clean is left.

Actuation lives in ``tick()`` — explicitly driven (bench loop, test,
ops cadence), never a hidden thread: finish drains, fire canary
probes, refresh shed latches, drain-and-restart canary-flagged
replicas, and feed the autoscaler, actuating its decision (spawn a new
slot, or drain the cheapest one down). Recovery lives in ``result()``:
a request stranded on a killed replica surfaces as ``ReplicaDead`` and
is resubmitted elsewhere (``router_requeue_total``), with the router's
own goodput ledger charging the *end-to-end* wait — a requeue stall is
a real TTFT hit to the client even though the second replica's engine
never saw it.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from elephas_tpu import obs
from elephas_tpu.obs.slo import GoodputLedger
from elephas_tpu.serving.fleet.replica import (
    DRAINING,
    Replica,
    ReplicaDead,
)
from elephas_tpu.serving.fleet.replica_set import ReplicaSet
from elephas_tpu.serving.scheduler import QueueFull
from elephas_tpu.utils import locksan

__all__ = ["FleetUnavailable", "Router"]

#: Dispatch-cost weights: load leads, queue pressure seconds it, burn
#: is a tie-breaking nudge (the hard burn response is the shed latch,
#: not the cost term).
COST_QUEUE_WEIGHT = 0.5
COST_BURN_WEIGHT = 0.25
#: Burn saturates the cost term at critical territory (>6 is already
#: page-worthy; beyond that the number carries no routing signal).
BURN_COST_CAP = 8.0
#: Decode-tier handoff targeting adds KV pressure at full weight — a
#: decode replica out of free blocks evicts prefix state on import,
#: which is exactly the waste tiering exists to avoid.
COST_KV_WEIGHT = 1.0
#: Handoff latency samples kept for the /tiers p50/p99 (bounded ring).
HANDOFF_SAMPLES = 512


class FleetUnavailable(RuntimeError):
    """No serving replica exists (all dead/draining) — distinct from
    ``QueueFull``, where replicas exist but all rejected admission."""


class _Assignment:
    """Where one routed request currently lives (mutable: requeue
    re-points it at a new replica/engine id).

    ``stage`` tracks the disaggregated pipeline position: ``"mono"``
    (classic single-replica serving), ``"prefill"`` (waiting on a
    prefill-tier KV export), or ``"decode"`` (handed off; waiting on
    the decode replica's result). Requeues and handoff failures
    degrade the stage back to ``"mono"``.
    """

    __slots__ = ("router_id", "prompt", "kwargs", "session", "canary",
                 "replica_id", "engine_rid", "t_router", "t_engine",
                 "resubmits", "stage")

    def __init__(self, router_id: int, prompt: Sequence[int],
                 kwargs: Dict[str, Any], session: Optional[str],
                 canary: bool, replica_id: str, engine_rid: int,
                 t_router: float, t_engine: float, stage: str = "mono"):
        self.router_id = router_id
        self.prompt = prompt
        self.kwargs = kwargs
        self.session = session
        self.canary = canary
        self.replica_id = replica_id
        self.engine_rid = engine_rid
        self.t_router = t_router
        self.t_engine = t_engine
        self.resubmits = 0
        self.stage = stage


class _RouterOutcome:
    """Duck-typed ``GenerationResult`` for the router's own goodput
    ledger (``GoodputLedger.record`` reads only these three fields):
    same objective semantics, but TTFT measured from the *router*
    submit, so dispatch and requeue stalls land in the number."""

    __slots__ = ("status", "ttft_s", "itl_s_avg")

    def __init__(self, status: str, ttft_s: Optional[float],
                 itl_s_avg: Optional[float]):
        self.status = status
        self.ttft_s = ttft_s
        self.itl_s_avg = itl_s_avg


class Router:
    """Load- and goodput-driven admission frontend over a ``ReplicaSet``."""

    def __init__(self, replica_set: ReplicaSet, *,
                 clock=None, autoscaler=None,
                 canary_fail_threshold: int = 1,
                 qos=None):
        if canary_fail_threshold < 1:
            raise ValueError(
                f"canary_fail_threshold must be >= 1, "
                f"got {canary_fail_threshold}")
        self.replica_set = replica_set
        self.clock = replica_set.clock if clock is None else clock
        self.autoscaler = autoscaler
        self.canary_fail_threshold = canary_fail_threshold
        #: Optional multi-tenant admission policy (fleet.qos.QoSPolicy):
        #: token buckets + weighted fair share gate every non-canary
        #: submit; priority-0 tenants may preempt queued lower-priority
        #: work when every replica rejects admission.
        self.qos = qos
        #: Router-relative goodput: the client's view of the fleet,
        #: including dispatch and requeue stalls no single engine sees.
        self.slo = GoodputLedger(clock=self.clock)

        self._ids = itertools.count()
        self._lock = locksan.make_lock("Router._lock")
        self._assignments: Dict[int, _Assignment] = {}
        self._sessions: Dict[str, str] = {}
        self._affinity: Dict[str, Dict[str, int]] = {}
        self.ops = None
        #: ``RolloutController`` once attached — feeds the ops
        #: ``/rollout`` route and is ticked alongside the router.
        self.rollout = None

        # Plain-int mirrors readable without a registry scrape; the
        # counters are the dashboard surface.
        self.requests = 0
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.requeues = 0
        self.handoffs = 0
        self.handoff_fails = 0
        self.preemptions = 0
        #: Bounded handoff-latency ring (seconds) for /tiers p50/p99.
        self._handoff_s: List[float] = []
        reg = obs.default_registry()
        self._m_requests = reg.counter(
            "router_requests_total",
            help="requests admitted through the fleet router")
        self._m_hit = reg.counter(
            "affinity_hit_total",
            help="session follow-ups dispatched to the replica already "
                 "holding the session's KV state")
        self._m_miss = reg.counter(
            "affinity_miss_total",
            help="session follow-ups re-routed because the pinned "
                 "replica was draining, dead, or shedding")
        self._m_requeue = reg.counter(
            "router_requeue_total",
            help="in-flight requests resubmitted to another replica "
                 "after their replica died un-drained")
        self._m_handoff = reg.counter(
            "router_handoff_total",
            help="prefill-tier KV exports successfully imported by a "
                 "decode replica")
        self._m_handoff_fail = reg.counter(
            "router_handoff_fail_total",
            help="KV handoffs that failed (corrupt frame, no decode "
                 "capacity, dead target) and degraded to a local "
                 "re-prefill")
        self._m_preempt = reg.counter(
            "router_preempt_total",
            help="queued lower-priority requests cancelled to seat a "
                 "priority-0 submit")
        self._g_imbalance = reg.gauge(
            "fleet_tier_imbalance",
            help="max minus min average load score across populated "
                 "serving tiers (0 with fewer than two tiers)")
        self._g_handoff_p99 = reg.gauge(
            "fleet_handoff_seconds_p99",
            help="p99 of recent prefill->decode KV handoff latency")

    # -- dispatch ----------------------------------------------------------

    def dispatch_cost(self, rep: Replica) -> float:
        """Composite per-replica cost; lower routes first."""
        burn = min(rep.worst_burn(), BURN_COST_CAP) / BURN_COST_CAP
        return (rep.load_score()
                + COST_QUEUE_WEIGHT * rep.queue_frac()
                + COST_BURN_WEIGHT * burn)

    def decode_cost(self, rep: Replica) -> float:
        """Handoff-target cost: the dispatch composite plus KV-pool
        pressure — the signal that actually predicts whether an import
        will evict prefix state."""
        return self.dispatch_cost(rep) + COST_KV_WEIGHT * rep.kv_pressure()

    def _disagg_active(self) -> bool:
        """Disaggregated routing is on when both tiers have a serving
        replica. Canaries always serve mono-style (one replica end to
        end) — a blackbox probe must measure one replica, not the
        pipeline."""
        return bool(self.replica_set.serving("prefill")
                    and self.replica_set.serving("decode"))

    def _dispatch_order(
            self, pinned: Optional[str]) -> Tuple[List[Replica], bool]:
        """Serving replicas in dispatch order, plus whether the
        session pin held.

        Clean replicas rank by cost ahead of every shedding one
        (deterministic id tie-break). A healthy pinned replica jumps
        the whole ranking; a shedding/draining/dead pin does not —
        that's the explicit affinity miss."""
        serving = self.replica_set.serving()
        if not serving:
            raise FleetUnavailable("no serving replica")
        ranked = sorted(
            serving,
            key=lambda r: (r.shedding, self.dispatch_cost(r), r.replica_id))
        if pinned is not None:
            lead = next(
                (r for r in ranked if r.replica_id == pinned), None)
            if lead is not None and not lead.shedding:
                return ([lead] + [r for r in ranked if r is not lead],
                        True)
        return ranked, False

    def _tier_order(self, tier: str) -> List[Replica]:
        """Serving replicas of one tier in dispatch order (shed latch
        first, then cost, then id — same ranking as mono dispatch)."""
        return sorted(
            self.replica_set.serving(tier),
            key=lambda r: (r.shedding, self.dispatch_cost(r), r.replica_id))

    def _admit_on(self, order: List[Replica], prompt: Sequence[int],
                  kwargs: Dict[str, Any],
                  prefill: bool) -> Tuple[Replica, int]:
        """Try each candidate in order; first admission wins. Raises
        the last ``QueueFull`` when every one rejected."""
        last_full = None
        for candidate in order:
            try:
                engine_rid = candidate.engine.submit(
                    prompt, prefill_only=prefill, **kwargs)
            except QueueFull as err:
                last_full = err
                continue
            return candidate, engine_rid
        if last_full is None:
            raise FleetUnavailable("no serving replica")
        raise last_full

    def _try_preempt(self, beneficiary: Optional[str],
                     replica_ids: List[str]) -> Optional[Replica]:
        """Cancel one still-queued lower-priority request on one of
        ``replica_ids`` to free an admission seat. Lowest-priority
        (highest class number) victims go first; admitted work is
        never touched (``engine.cancel`` only yanks queued requests —
        the victim's waiter sees a ``"preempted"`` result and the
        router redispatches it). Returns the replica whose seat was
        freed, or None."""
        if self.qos is None:
            return None
        bene_prio = self.qos.priority(beneficiary)
        with self._lock:
            candidates = [a for a in self._assignments.values()
                          if a.replica_id in replica_ids
                          and not a.canary]
        candidates.sort(
            key=lambda a: -self.qos.priority(a.kwargs.get("tenant")))
        for victim in candidates:
            v_tenant = victim.kwargs.get("tenant")
            if self.qos.priority(v_tenant) <= bene_prio:
                break  # sorted: nothing lower-priority remains
            rep = self.replica_set.get(victim.replica_id)
            if rep.engine.cancel(victim.engine_rid):
                self.preemptions += 1
                self._m_preempt.inc()
                self.qos.note_preempted(v_tenant)
                obs.default_flight_recorder().note(
                    "tenant_preempted", "warn", tenant=v_tenant,
                    beneficiary=beneficiary, replica=victim.replica_id,
                    router_id=victim.router_id)
                return rep
        return None

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               *, session: Optional[str] = None,
               timeout_s: Optional[float] = None,
               canary: bool = False,
               tenant: Optional[str] = None) -> int:
        """Route one request; returns a router-scoped request id.

        ``tenant`` names the account billed for the request in the
        assigned replica's cost ledger. It rides the assignment's
        replay kwargs, so a requeue-on-death resubmits with the SAME
        tag — attribution survives mid-flight replica kills.

        With both a prefill and a decode tier serving (and ``qos`` not
        throttling the tenant), a non-canary request is dispatched to
        the prefill tier; ``result()`` drives the KV handoff to a
        decode replica. Raises ``FleetUnavailable`` when no replica is
        serving, ``AdmissionThrottled`` when QoS refuses the tenant,
        or the last replica's ``QueueFull`` when every one rejected
        admission (after a failed preemption attempt, for priority-0
        tenants).
        """
        t_router = self.clock()
        if self.qos is not None and not canary:
            self.qos.try_admit(tenant, len(prompt) + max_new_tokens)
        with self._lock:
            pinned = None if session is None else self._sessions.get(session)
        disagg = (not canary) and self._disagg_active()
        kwargs = {"max_new_tokens": max_new_tokens, "timeout_s": timeout_s,
                  "canary": canary, "tenant": tenant}
        if disagg:
            # The session pin (if any) points at a decode replica
            # holding KV state; prefill dispatch ignores it — the
            # handoff targeting honors it instead (_do_handoff).
            order = self._tier_order("prefill")
            pin_held = False
        else:
            order, pin_held = self._dispatch_order(pinned)
        try:
            rep, engine_rid = self._admit_on(order, prompt, kwargs,
                                             prefill=disagg)
        except QueueFull:
            if (self.qos is None or canary
                    or self.qos.priority(tenant) != 0):
                raise
            freed = self._try_preempt(
                tenant, [r.replica_id for r in order])
            if freed is None:
                raise
            rep, engine_rid = self._admit_on([freed], prompt, kwargs,
                                             prefill=disagg)

        self.requests += 1
        self._m_requests.inc()
        if pinned is not None and not disagg:
            card = self._affinity.setdefault(
                rep.replica_id, {"hits": 0, "misses": 0})
            if pin_held and rep.replica_id == pinned:
                self.affinity_hits += 1
                card["hits"] += 1
                self._m_hit.inc()
            else:
                self.affinity_misses += 1
                card["misses"] += 1
                self._m_miss.inc()
        rep.note_dispatch()

        router_id = next(self._ids)
        asg = _Assignment(
            router_id, list(prompt), kwargs,
            session, canary, rep.replica_id, engine_rid,
            t_router, self.clock(),
            stage="prefill" if disagg else "mono")
        with self._lock:
            self._assignments[router_id] = asg
            if session is not None and not disagg:
                self._sessions[session] = rep.replica_id
        return router_id

    # -- results + recovery ------------------------------------------------

    def result(self, router_id: int,
               timeout_s: Optional[float] = None):
        """Claim a routed result, requeueing across replica death.

        A ``ReplicaDead`` from the assigned replica resubmits the
        request on the next-best replica and keeps waiting — the
        client sees one slower result, never the outage. A
        ``"prefill"``-stage assignment first waits for the KV export,
        then drives the handoff to a decode replica (falling back to a
        local re-prefill on any handoff failure); a ``"preempted"``
        result redispatches under fair share. Either way the client
        sees exactly one terminal result.
        """
        with self._lock:
            asg = self._assignments.get(router_id)
        if asg is None:
            raise KeyError(f"unknown router request id {router_id}")
        deadline = (None if timeout_s is None
                    else self.clock() + timeout_s)
        while True:
            rep = self.replica_set.get(asg.replica_id)
            remaining = (None if deadline is None
                         else max(0.0, deadline - self.clock()))
            if asg.stage == "prefill":
                try:
                    data = rep.handoff(asg.engine_rid,
                                       timeout_s=remaining)
                except ReplicaDead:
                    self._requeue(asg)
                    continue
                if isinstance(data, dict):
                    self._do_handoff(asg, rep, data, deadline=deadline)
                    continue
                res = data  # terminated on the prefill engine
                rep.note_done()
            else:
                try:
                    res = rep.result(asg.engine_rid, timeout_s=remaining)
                except ReplicaDead:
                    self._requeue(asg)
                    continue
                rep.note_done()
                if res.status == "preempted":
                    self._redispatch(asg, deadline=deadline)
                    continue
            with self._lock:
                self._assignments.pop(router_id, None)
            if not asg.canary:
                ttft = (None if res.ttft_s is None
                        else (asg.t_engine - asg.t_router) + res.ttft_s)
                self.slo.record(
                    _RouterOutcome(res.status, ttft, res.itl_s_avg))
            return res

    def _do_handoff(self, asg: _Assignment, rep: Replica,
                    data: Dict[str, Any],
                    deadline: Optional[float] = None) -> None:
        """Ship a claimed KV export to the best decode replica; on any
        failure, degrade to a local re-prefill (``_redispatch``) — the
        request is never lost, only slower (and token-identical either
        way: the fallback recomputes the same prompt on the same
        params and seed).

        Latency is measured from export claim to accepted import —
        encode, (in-process) transfer, validation, and the device
        staging of every block land inside the number. A decode tier
        that is merely FULL is backpressure, not failure: the loop
        re-ranks and retries with bounded sleeps until ``deadline``
        (same discipline as ``_redispatch``) — only a structural
        defect (corrupt frame, import rejection), an empty tier, or
        deadline exhaustion degrades to the local re-prefill.
        """
        from elephas_tpu.parameter.wire import WireFormatError
        from elephas_tpu.serving.handoff import encode_handoff

        t0 = self.clock()
        failure = None
        try:
            frame = encode_handoff(data).tobytes()
        except WireFormatError as exc:
            frame, failure = None, repr(exc)
        while frame is not None:
            targets = sorted(
                self.replica_set.serving("decode"),
                key=lambda r: (r.shedding, self.decode_cost(r),
                               r.replica_id))
            with self._lock:
                pinned = (None if asg.session is None
                          else self._sessions.get(asg.session))
            if pinned is not None:
                lead = next(
                    (r for r in targets if r.replica_id == pinned), None)
                if lead is not None and not lead.shedding:
                    targets = [lead] + [r for r in targets if r is not lead]
            structural = False
            retry_after = None
            for cand in targets:
                try:
                    new_rid = cand.engine.submit_handoff(
                        frame, canary=asg.canary)
                except QueueFull as err:
                    failure = repr(err)
                    retry_after = (err.retry_after if retry_after is None
                                   else min(retry_after, err.retry_after))
                    continue
                except (WireFormatError, ValueError) as err:
                    failure = repr(err)
                    structural = True
                    break  # structural defect; other targets won't help
                t1 = self.clock()
                self.handoffs += 1
                self._m_handoff.inc()
                self._handoff_s.append(t1 - t0)
                del self._handoff_s[:-HANDOFF_SAMPLES]
                rep.note_done()
                cand.note_dispatch()
                obs.default_flight_recorder().note(
                    "kv_handoff", "info", tenant=asg.kwargs.get("tenant"),
                    src=rep.replica_id, dst=cand.replica_id,
                    blocks=data["export"]["blocks"],
                    matched=data["matched"],
                    ms=round((t1 - t0) * 1e3, 3))
                with self._lock:
                    asg.replica_id = cand.replica_id
                    asg.engine_rid = new_rid
                    asg.stage = "decode"
                    asg.t_engine = t1
                    if asg.session is not None:
                        self._sessions[asg.session] = cand.replica_id
                return
            if not targets:
                failure = "no serving decode replica"
            if structural or retry_after is None:
                break
            if deadline is not None and self.clock() >= deadline:
                break
            time.sleep(min(max(retry_after, 0.01), 0.05))
            # Queue wait is backpressure, not transport: restart the
            # latency sample so handoff_p99 keeps measuring the
            # encode→import path, not how long the decode tier was full.
            t0 = self.clock()
        self.handoff_fails += 1
        self._m_handoff_fail.inc()
        obs.default_flight_recorder().note(
            "tier_handoff_fail", "warn", tenant=asg.kwargs.get("tenant"),
            src=rep.replica_id, reason=failure,
            router_id=asg.router_id)
        rep.note_done()
        self._redispatch(asg, deadline=deadline)

    def _redispatch(self, asg: _Assignment,
                    deadline: Optional[float] = None) -> None:
        """Re-run dispatch for an assignment whose replica already
        released it (handoff failure, preemption): mono-style, to any
        serving replica — correctness over tiering when the pipeline
        degrades. The caller has already ``note_done``d the old
        replica.

        A full fleet is retried with bounded sleeps until ``deadline``
        — a preempted victim often races the very preemptor that freed
        its seat, and losing the request to that race would turn a
        deferral into a failure."""
        while True:
            order, _ = self._dispatch_order(None)
            try:
                rep, engine_rid = self._admit_on(
                    order, asg.prompt, asg.kwargs, prefill=False)
                break
            except QueueFull as err:
                if deadline is not None and self.clock() >= deadline:
                    raise
                time.sleep(min(max(err.retry_after, 0.01), 0.05))
        rep.note_dispatch()
        with self._lock:
            asg.replica_id = rep.replica_id
            asg.engine_rid = engine_rid
            asg.stage = "mono"
            asg.resubmits += 1
            asg.t_engine = self.clock()
            if asg.session is not None:
                self._sessions[asg.session] = rep.replica_id

    def _requeue(self, asg: _Assignment) -> None:
        """Move a stranded assignment off its dead replica."""
        dead_id = asg.replica_id
        self.replica_set.get(dead_id).note_done()
        order, _ = self._dispatch_order(None)
        rep = None
        engine_rid = None
        last_full = None
        for candidate in order:
            if candidate.replica_id == dead_id:
                continue
            try:
                engine_rid = candidate.engine.submit(
                    asg.prompt, **asg.kwargs)
            except QueueFull as err:
                last_full = err
                continue
            rep = candidate
            break
        if rep is None:
            if last_full is not None:
                raise last_full
            raise ReplicaDead(dead_id, asg.engine_rid)
        rep.note_dispatch()
        self.requeues += 1
        self._m_requeue.inc()
        # The replay carried the original tenant tag (it lives in
        # asg.kwargs); charge the requeue itself to that tenant on the
        # RECEIVING replica's ledger, where the rest of the request's
        # costs will now accrue.
        costs = getattr(rep.engine, "costs", None)
        if costs is not None:
            costs.record_requeue(asg.kwargs.get("tenant"))
        with self._lock:
            asg.replica_id = rep.replica_id
            asg.engine_rid = engine_rid
            # The replay is a plain submit — a prefill-stage
            # assignment degrades to mono on its new replica (its KV
            # export died with the old one).
            asg.stage = "mono"
            asg.resubmits += 1
            asg.t_engine = self.clock()
            if (asg.session is not None
                    and self._sessions.get(asg.session) == dead_id):
                self._sessions[asg.session] = rep.replica_id

    # -- actuation ---------------------------------------------------------

    def tick(self, now: Optional[float] = None,
             *, probe: bool = False) -> Dict[str, Any]:
        """One actuation pass (explicitly driven — no hidden thread).

        1. Close out drains whose replicas went idle; restart the
           canary-flagged ones (autoscaler drains stay down).
        2. Per serving replica: optionally fire one blackbox canary
           probe, refresh the latched burn alerts (shed state), and
           drain-and-restart any replica with fresh canary failures.
        3. Feed the worst serving burn to the autoscaler and actuate
           its decision.

        Returns a summary of what actuated, for benches and logs.
        """
        now = self.clock() if now is None else now
        actions: Dict[str, Any] = {
            "drain_finished": [], "restarted": [], "canary_drained": [],
            "scale": None,
        }
        for rep in list(self.replica_set.replicas.values()):
            if rep.state == DRAINING and rep.maybe_finish_drain():
                actions["drain_finished"].append(rep.replica_id)
                if rep.pending_restart and not rep.scale_down:
                    rep.restart(reason="canary")
                    actions["restarted"].append(rep.replica_id)
        for rep in self.replica_set.serving():
            if probe and rep.canary is not None:
                rep.canary.probe()
            rep.evaluate_alerts(now)
            fresh = (0 if rep.canary is None
                     else rep.canary.failures - rep.seen_canary_failures)
            if fresh >= self.canary_fail_threshold:
                rep.seen_canary_failures = rep.canary.failures
                rep.pending_restart = True
                rep.drain(reason="canary_failures")
                actions["canary_drained"].append(rep.replica_id)
        if self.autoscaler is not None:
            serving = self.replica_set.serving()
            if serving:
                burn = max(r.worst_burn() for r in serving)
                decision = self.autoscaler.observe(
                    burn=burn, n_replicas=len(serving), now=now)
                if decision == "up":
                    self.replica_set.spawn()
                elif decision == "down":
                    victim = min(
                        serving,
                        key=lambda r: (self.dispatch_cost(r),
                                       r.replica_id))
                    victim.scale_down = True
                    victim.drain(reason="scale_down")
                actions["scale"] = decision
        if self.rollout is not None:
            actions["rollout"] = self.rollout.tick(now)
        return actions

    # -- introspection -----------------------------------------------------

    def session_replica(self, session: str) -> Optional[str]:
        """Which replica holds this session's KV state (None if the
        session is unknown) — benches use it to aim kills."""
        with self._lock:
            return self._sessions.get(session)

    def replicas_doc(self) -> Dict[str, Any]:
        """The ``/replicas`` ops document: per-replica signal cards
        plus router counters and the autoscaler's policy card."""
        with self._lock:
            sessions = len(self._sessions)
            in_flight = len(self._assignments)
            affinity = {rid: dict(card)
                        for rid, card in self._affinity.items()}
        replicas: Dict[str, Any] = {}
        for rid, rep in self.replica_set.replicas.items():
            card = rep.signals()
            card["affinity"] = affinity.get(rid, {"hits": 0, "misses": 0})
            replicas[rid] = card
        return {
            "replicas": replicas,
            "router": {
                "requests": self.requests,
                "affinity_hits": self.affinity_hits,
                "affinity_misses": self.affinity_misses,
                "requeues": self.requeues,
                "handoffs": self.handoffs,
                "handoff_fails": self.handoff_fails,
                "preemptions": self.preemptions,
                "sessions": sessions,
                "in_flight": in_flight,
            },
            "autoscale": (None if self.autoscaler is None
                          else self.autoscaler.snapshot()),
        }

    @staticmethod
    def _pctl(samples: List[float], q: float) -> Optional[float]:
        if not samples:
            return None
        ordered = sorted(samples)
        idx = min(len(ordered) - 1,
                  int(q * len(ordered)))  # host-ok: host-side latencies
        return ordered[idx]

    def tiers_doc(self) -> Dict[str, Any]:
        """The ``/tiers`` ops document: per-tier membership and
        pressure, handoff latency/failure stats, and the QoS policy
        card. Publishing refreshes the ``fleet_tier_imbalance`` and
        ``fleet_handoff_seconds_p99`` gauges (the alert plane's
        inputs)."""
        tiers: Dict[str, Any] = {}
        for rep in self.replica_set.replicas.values():
            card = tiers.setdefault(rep.tier, {
                "replicas": [], "serving": 0,
                "avg_load": None, "avg_kv_pressure": None,
                "_loads": [], "_kv": []})
            card["replicas"].append(rep.replica_id)
            if rep.state == "serving":
                card["serving"] += 1
                card["_loads"].append(rep.load_score())
                card["_kv"].append(rep.kv_pressure())
        for card in tiers.values():
            loads, kv = card.pop("_loads"), card.pop("_kv")
            if loads:
                card["avg_load"] = sum(loads) / len(loads)
                card["avg_kv_pressure"] = sum(kv) / len(kv)
        avgs = [c["avg_load"] for c in tiers.values()
                if c["avg_load"] is not None]
        imbalance = (max(avgs) - min(avgs)) if len(avgs) >= 2 else 0.0
        self._g_imbalance.set(imbalance)
        samples = list(self._handoff_s)
        p50 = self._pctl(samples, 0.50)
        p99 = self._pctl(samples, 0.99)
        self._g_handoff_p99.set(0.0 if p99 is None else p99)
        return {
            "disagg_active": self._disagg_active(),
            "tiers": tiers,
            "imbalance": imbalance,
            "handoffs": {
                "count": self.handoffs,
                "fails": self.handoff_fails,
                "p50_ms": None if p50 is None else round(p50 * 1e3, 3),
                "p99_ms": None if p99 is None else round(p99 * 1e3, 3),
            },
            "preemptions": self.preemptions,
            "qos": None if self.qos is None else self.qos.snapshot(),
        }

    def mount_ops(self, port: int = 0, host: Optional[str] = None):
        """Serve the router's own ops endpoint (role ``router``): the
        fleet aggregator polls it like any process and picks the
        ``/replicas`` roster out of the tolerant scrape."""
        if self.ops is not None:
            return self.ops
        from elephas_tpu.obs.opsd import OpsServer

        self.ops = OpsServer(
            port=port, host=host, role="router",
            vars_fn=lambda: {
                "role": "router",
                "replicas": len(self.replica_set),
                "serving": len(self.replica_set.serving()),
            },
            health_fn=lambda: {
                "healthy": bool(self.replica_set.serving()),
                "serving": len(self.replica_set.serving()),
                "requests": self.requests,
                "requeues": self.requeues,
            },
            slo_fn=self.slo.snapshot,
            replicas_fn=self.replicas_doc,
            tenants_fn=self._tenants_doc,
            tiers_fn=self.tiers_doc,
            rollout_fn=self._rollout_doc,
        ).start()
        return self.ops

    def attach_rollout(self, controller) -> None:
        """Adopt a ``RolloutController`` for this fleet: its ``doc()``
        serves the ops ``/rollout`` route (federated by the fleet
        aggregator), and each ``Router.tick`` drives one controller
        tick so delivery policy shares the router's actuation cadence.
        The controller stays usable standalone (``start_ticker``)."""
        self.rollout = controller

    def _rollout_doc(self) -> Dict[str, Any]:
        if self.rollout is None:
            return {"active": False, "phase": "idle",
                    "approved_version": None, "candidate_version": None,
                    "canary": None, "versions": {}, "skew": 0,
                    "events": [], "digest": None}
        return self.rollout.doc()

    def _tenants_doc(self) -> Dict[str, Any]:
        """Fleet-wide ``/tenants``: tenant-wise union of every serving
        replica's cost ledger (counters summed, goodput ratio = worst
        across replicas, burn = worst) — the same merge the
        ``FleetAggregator`` applies to scraped per-process docs."""
        from elephas_tpu.obs.tenancy import merge_tenant_docs

        docs = []
        for rep in self.replica_set.serving():
            costs = getattr(rep.engine, "costs", None)
            if costs is not None and costs.tenants():
                costs.evaluate_alerts(self.clock())
                docs.append(costs.snapshot())
        return merge_tenant_docs(docs)

    def unmount_ops(self) -> None:
        if self.ops is not None:
            self.ops.stop()
            self.ops = None

    def close(self) -> None:
        """Teardown for benches/tests."""
        self.unmount_ops()
        self.replica_set.close()
