"""Slot-based KV-cache pool for continuous batching.

The pool owns ONE cache pytree of fixed shape — per layer,
``cached_key``/``cached_value`` of (max_slots, heads, max_len, head_dim)
plus per-slot ``cache_index``/``pos_index`` (max_slots,) vectors — so the
compiled decode step's operand shapes never change as sequences come and
go. Admission writes a finished prefill's batch-1 cache into a free
slot's row (a jitted dynamic_update_slice with the slot id TRACED — one
compile covers every slot); eviction just returns the slot id to the
free list, since the next admit overwrites the row wholesale.

The cache pytree is DONATED to every program that rewrites it — the
admission ``_write_slot`` here and the engine's decode step — so XLA
updates the pool in place instead of materializing a full copy of every
layer's K/V each token (the copy was PR 1's single biggest per-step
cost after the host sync). Donation makes the OLD buffers poison: any
read through a stale reference raises, so ``self._cache`` is private
and the ``cache`` property guards every access with an explicit
use-after-donate check (a stale read would otherwise surface as an
opaque ``Array has been deleted`` deep inside XLA).

Per-slot state the model consumes each step:

- ``cache_index``/``pos_index`` — the column the slot's next token
  writes (advanced by the apply itself, per row — ONLY for rows the
  decode step's ``active`` mask marks occupied; free slots' vectors
  freeze so they can't march past ``max_len`` between admissions),
- ``pad``        — the slot's left-pad column count (prompts are
  left-padded to the engine's fixed prefill length so prefill is one
  compiled program; the pad columns stay masked out of attention for
  the sequence's whole lifetime).

Inactive slots ride along in the decode batch (their logits are
discarded and their rows rewritten on admit) — the price of a
fixed-shape program, and exactly the slot semantics of continuous
batching servers (Orca-style iteration-level scheduling).
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp


def _vectorize_indices(cache, max_slots: int):
    """Replace every scalar cache index leaf with a per-slot vector."""

    def fix(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("cache_index", "pos_index"):
            assert leaf.ndim == 0, f"{name} already vectorized?"
            return jnp.zeros((max_slots,), jnp.int32)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _write_slot(pool_cache, pad, prefill_cache, slot, pad_offset):
    """Copy a batch-1 prefill cache into ``slot``'s row of the pool.

    ``slot`` is a traced int32 — one compiled program admits to any
    slot. Index leaves (pool (S,), prefill scalar) are distinguished
    from data leaves (pool (S, ...), prefill (1, ...)) by rank. The
    pool cache and pad vector are DONATED: XLA writes the slot row in
    place, so admission costs one row, not a whole-pool copy.
    """

    def write(pool_leaf, pre_leaf):
        if pre_leaf.ndim == 0:  # cache_index / pos_index
            return jax.lax.dynamic_update_slice(
                pool_leaf, pre_leaf[None].astype(pool_leaf.dtype), (slot,)
            )
        return jax.lax.dynamic_update_slice(
            pool_leaf, pre_leaf.astype(pool_leaf.dtype),
            (slot,) + (0,) * (pre_leaf.ndim - 1),
        )

    new_cache = jax.tree_util.tree_map(write, pool_cache, prefill_cache)
    new_pad = jax.lax.dynamic_update_slice(pad, pad_offset[None], (slot,))
    return new_cache, new_pad


class DonatedBufferError(RuntimeError):
    """A pool cache reference was read after its buffers were donated."""


class KVCachePool:
    """Fixed-shape KV cache + slot bookkeeping for the serving engine.

    ``decode_module``: a ``TransformerLM`` with ``decode=True``.
    ``max_slots``: decode batch width (concurrent sequences).
    ``max_len``: cache columns per slot — an admitted sequence may run
    to ``prefill_len + generated <= max_len``.

    The live cache is read through the ``cache`` property and replaced
    with ``swap(new_cache)`` after every donating program. The property
    refuses to hand out donated (deleted) buffers — the failure mode
    donation introduces is a stale alias kept across a swap, and that
    must fail loudly at the POOL boundary, not as a deep XLA error.
    """

    def __init__(self, decode_module, max_slots: int, max_len: int):
        from elephas_tpu.models.transformer import make_decode_cache

        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_slots = max_slots
        self.max_len = max_len
        self._cache = _vectorize_indices(
            make_decode_cache(decode_module, max_slots, max_len), max_slots
        )
        self._pad = jnp.zeros((max_slots,), jnp.int32)
        self._free: List[int] = list(range(max_slots))
        self.admitted_total = 0  # lifetime admissions (slot reuse visible)

    # -- donation-guarded cache access -------------------------------------

    @staticmethod
    def _guard(tree, name: str):
        # One leaf suffices: every leaf of a donated pytree is deleted
        # by the same program call.
        leaf = jax.tree_util.tree_leaves(tree)[0]
        if getattr(leaf, "is_deleted", lambda: False)():
            raise DonatedBufferError(
                f"KV pool {name} was donated to a compiled program and "
                "its buffers are gone; use the value returned by that "
                "program (the engine swaps it back via pool.swap)"
            )
        return tree

    @property
    def cache(self):
        """The live cache pytree (raises ``DonatedBufferError`` if the
        held buffers were donated without a ``swap``)."""
        return self._guard(self._cache, "cache")

    @property
    def pad(self):
        """Per-slot left-pad counts, same donation guard as ``cache``."""
        return self._guard(self._pad, "pad")

    def swap(self, new_cache, new_pad=None) -> None:
        """Install the cache (and optionally pad) a donating program
        returned. The old references are dead the moment the program was
        dispatched — this is the only legal way to keep the pool live."""
        self._cache = new_cache
        if new_pad is not None:
            self._pad = new_pad

    # -- slot bookkeeping --------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_count(self) -> int:
        return self.max_slots - len(self._free)

    def active_slots(self) -> List[int]:
        """Occupied slot ids, ascending (the decode step's active mask)."""
        free = set(self._free)
        return [s for s in range(self.max_slots) if s not in free]

    def acquire(self) -> Optional[int]:
        """Claim a free slot id, or None when the pool is saturated."""
        if not self._free:
            return None
        return self._free.pop()

    def admit(self, slot: int, prefill_cache, pad_offset: int) -> None:
        """Write a finished batch-1 prefill into ``slot`` and record its
        left-pad count. The prefill cache's scalar indices carry the
        write position (= prefill length) into the slot's vectors."""
        self.swap(*_write_slot(
            self.cache, self.pad, prefill_cache, jnp.int32(slot),
            jnp.int32(pad_offset),
        ))
        self.admitted_total += 1

    def release(self, slot: int) -> None:
        """Return ``slot`` to the free list. No device work: the row's
        stale contents are overwritten wholesale by the next admit."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        if not 0 <= slot < self.max_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.max_slots})")
        self._free.append(slot)
